"""Multi-host serving fabric (ISSUE 18 tentpole): family-sticky routing,
exactly-once handoff, fleet-level scaling.

One serving host is a DecodeServer + ContinuousBatcher; a fleet is N of
them behind this router.  The placement unit is the bucket FAMILY (see
``session.bucket_family``), never the session or the request: co-family
sessions ride ONE cell-fused dispatch (ISSUE 15), so scattering a family
across hosts would silently de-fuse it back into per-session rounds.
The router therefore consistent-hashes family keys onto host labels and
keeps every session of a family on its owner host.

  HashRing      sha1 vnode ring over host labels; ``order(key)`` yields
                the distinct labels in ring order — [owner, successor,
                ...] — so a host loss promotes the standing replication
                target, and placements move minimally when hosts change.
  FleetRouter   the data plane + control plane in one object:
                  * data plane — an asyncio TCP front speaking the exact
                    client wire protocol.  hello/ping answer locally;
                    decode / stream_* frames are wrapped in the
                    ``BIN_KIND_ROUTED`` envelope (family + placement
                    epoch, payload verbatim — bitplanes never re-encoded)
                    and forwarded to the family's owner over a per-client
                    backend link; responses relay back matched by wire
                    id.  A ``route_stale`` refusal from the owner's epoch
                    fence re-resolves placement and re-forwards — a
                    partitioned router cannot double-decode.
                  * control plane — a daemon loop that (a) re-asserts
                    placement epochs to every live host (``family_adopt``
                    own/fence broadcasts, idempotent), (b) incrementally
                    replicates each host's answered journal + stream
                    ledgers to the family successors (``journal_export``
                    watermark pulls -> ``journal_import`` pushes), and
                    (c) watches the federation gateway's ``host_down:*``
                    deadman alerts: when one fires, the dead host's
                    families gate, the buffered journal delta is flushed
                    to the successor (BLOCKING until the watermark
                    catches up — never serving stale answers), ownership
                    re-adopts at epoch+1, and the gates open.  Clients
                    ride through purely on their existing reconnect +
                    idempotent-resubmit machinery.
  FleetScaler   drives each host's AutoScaler and, off the gateway's
                merged load signal, live-moves the smallest family from
                the hottest host to the coldest (same fence/replicate/
                adopt machinery, with a live source).
  LocalFleet    an N-host in-process fleet (per-host batcher + server +
                ops plane, one FleetGateway, one FleetRouter) — the
                harness behind ``bench.py fleet`` and the fleet chaos
                acceptance tests, including the ``host_kill`` /
                ``journal_lag`` / ``router_partition`` chaos kinds.

Chaos sites (registered in utils.faultinject.SITES, lint rule R008):
``router_route`` fires per forwarded frame (``router_partition`` makes
ONE frame carry a deliberately stale epoch, proving the fence end to
end); ``router_replicate`` fires per journal push (``journal_lag`` fails
the push so the successor falls behind and the handoff must block);
``fleet_host_tick`` fires per LocalFleet chaos tick (``host_kill`` kills
the current owner of the first family mid-storm).
"""
from __future__ import annotations

import asyncio
import bisect
import hashlib
import json
import socket
import struct
import threading
import time

from ..utils import faultinject, resilience, telemetry
from . import fleet as fleet_mod
from . import ops
from .server import read_frame
from .wire import (
    HEADER,
    MAX_FRAME_BYTES,
    WIRE_CODEC_JSON,
    WIRE_CODECS,
    WIRE_MAGIC,
    _BIN_HEAD,
    encode_frame,
    encode_routed_payload,
    peek_response_id,
)

__all__ = [
    "HashRing", "ControlClient", "FleetRouter", "RouterHandle",
    "RouterFleetServer", "FleetScaler", "LocalFleet",
    "start_router_thread", "start_router_ops_thread",
]

# a frame refused by the owner's epoch fence is re-resolved and
# re-forwarded at most this many times before the refusal relays to the
# client (whose resubmit machinery then owns the retry)
MAX_STALE_REFORWARDS = 5


class HashRing:
    """Consistent hash over host labels, keyed by bucket-family strings.

    sha1-based (process-stable — builtin ``hash`` is salted per process,
    which would reshuffle every placement on restart) with ``vnodes``
    points per host so family load spreads evenly."""

    def __init__(self, labels, vnodes: int = 64):
        self.labels = sorted(str(lb) for lb in labels)
        if not self.labels:
            raise ValueError("HashRing needs at least one host label")
        points = []
        for label in self.labels:
            for v in range(int(vnodes)):
                points.append((self._hash(f"{label}#{v}"), label))
        points.sort()
        self._points = points
        self._keys = [p[0] for p in points]

    @staticmethod
    def _hash(text: str) -> int:
        return int.from_bytes(
            hashlib.sha1(text.encode("utf-8")).digest()[:8], "big")

    def order(self, key, exclude=()) -> list:
        """Distinct host labels in ring order from ``key``'s point,
        skipping ``exclude`` — ``[owner, successor, ...]``."""
        start = bisect.bisect_left(self._keys, self._hash(str(key)))
        seen: set = set()
        out: list = []
        n = len(self._points)
        for i in range(n):
            label = self._points[(start + i) % n][1]
            if label in seen or label in exclude:
                continue
            seen.add(label)
            out.append(label)
        return out


class ControlClient:
    """One-shot synchronous control-op client (``family_adopt`` /
    ``journal_export`` / ``journal_import``): a fresh socket per call, so
    a dead host fails THIS call and never poisons a pool.  Control ops
    are JSON v1 both ways (responses mirror the request codec)."""

    def __init__(self, address, timeout_s: float = 5.0):
        self.address = (str(address[0]), int(address[1]))
        self.timeout_s = float(timeout_s)

    def call(self, msg: dict) -> dict:
        with socket.create_connection(self.address,
                                      timeout=self.timeout_s) as sock:
            sock.settimeout(self.timeout_s)
            sock.sendall(encode_frame(msg))
            (length,) = HEADER.unpack(self._read_exact(sock, HEADER.size))
            if length > MAX_FRAME_BYTES:
                raise ValueError(f"control reply of {length} bytes exceeds "
                                 f"the {MAX_FRAME_BYTES}-byte cap")
            return json.loads(self._read_exact(sock, length)
                              .decode("utf-8"))

    @staticmethod
    def _read_exact(sock, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("control peer closed mid-frame")
            buf += chunk
        return buf


def _peek_header(payload: bytes) -> "dict | None":
    """Routing peek: the JSON header of one CLIENT payload (op / id /
    session / stream / profile) without unpacking any bitplane — v2
    decodes only the binary header's JSON, v1 costs a full JSON parse.
    None when malformed (the caller answers a structured error)."""
    try:
        if payload[:2] == WIRE_MAGIC:
            _, _, _, hlen = _BIN_HEAD.unpack_from(payload)
            obj = json.loads(
                payload[_BIN_HEAD.size:_BIN_HEAD.size + hlen]
                .decode("utf-8"))
        else:
            obj = json.loads(payload.decode("utf-8"))
        return obj if isinstance(obj, dict) else None
    except (struct.error, UnicodeDecodeError, json.JSONDecodeError,
            IndexError):
        return None


def _new_bucket() -> dict:
    return {"entries": [], "streams": {}, "programs": {}, "watermark": 0}


class _BackendLink:
    """One router->host connection, scoped to ONE client connection: wire
    ids are client-connection-scoped, so sharing a backend link across
    clients would collide response matching."""

    def __init__(self, conn: "_RouterConn", label: str, address):
        self.conn = conn
        self.label = label
        self.address = (str(address[0]), int(address[1]))
        self.reader = None
        self.writer = None
        self._pump: "asyncio.Task | None" = None
        self._wlock = asyncio.Lock()
        self.dead = False
        self._closing = False

    async def open(self) -> None:
        self.reader, self.writer = await asyncio.open_connection(
            *self.address)
        self._pump = asyncio.get_running_loop().create_task(
            self._pump_loop())

    async def send(self, frame: bytes) -> None:
        async with self._wlock:
            self.writer.write(frame)
            await self.writer.drain()
        telemetry.count("router.bytes_forwarded", len(frame))

    async def _pump_loop(self) -> None:
        try:
            while True:
                payload = await read_frame(self.reader)
                if payload is None:
                    break
                await self.conn.on_backend_payload(self.label, payload)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — pump death is a transport event
            telemetry.count("router.pump_errors")
        finally:
            self.dead = True
            if not self._closing:
                # backend died while the client lives: abort the client
                # transport so its reconnect + idempotent-resubmit
                # machinery takes over (exactly a dead host's signature)
                self.conn.abort()

    async def close(self) -> None:
        self._closing = True
        self.dead = True
        if self._pump is not None:
            self._pump.cancel()
            try:
                await self._pump
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        if self.writer is not None:
            try:
                self.writer.close()
                await self.writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass


class _RouterConn:
    """Per-client-connection state: the client writer, the lazy backend
    links, and the pending table matching relayed responses (and
    ``route_stale`` refusals) back to forwarded frames."""

    def __init__(self, router: "FleetRouter", writer, wlock):
        self.router = router
        self.writer = writer
        self.wlock = wlock
        self.links: dict = {}
        self.pending: dict = {}

    async def link(self, label: str) -> _BackendLink:
        lk = self.links.get(label)
        if lk is not None and not lk.dead:
            return lk
        lk = _BackendLink(self, label, self.router.hosts[label])
        try:
            await lk.open()
        except OSError:
            telemetry.count("router.backend_connect_errors")
            raise ConnectionError(
                f"backend host {label!r} is unreachable")
        self.links[label] = lk
        return lk

    async def write_local(self, obj: dict) -> None:
        frame = encode_frame(obj)
        async with self.wlock:
            self.writer.write(frame)
            await self.writer.drain()
        telemetry.count("router.bytes_tx", len(frame))

    async def relay(self, payload: bytes) -> None:
        async with self.wlock:
            self.writer.write(HEADER.pack(len(payload)) + payload)
            await self.writer.drain()
        telemetry.count("router.bytes_relayed",
                        len(payload) + HEADER.size)

    def abort(self) -> None:
        try:
            self.writer.transport.abort()
        except Exception:  # noqa: BLE001
            pass

    async def on_backend_payload(self, label: str, payload: bytes) -> None:
        rid = peek_response_id(payload)
        entry = self.pending.get(rid) if rid else None
        if payload[:1] == b"{":
            try:
                obj = json.loads(payload.decode("utf-8"))
            except (json.JSONDecodeError, UnicodeDecodeError):
                obj = None
            if isinstance(obj, dict):
                if obj.get("route_stale") and entry is not None:
                    # the owner's epoch fence refused the frame: our
                    # placement (or the frame's epoch) was stale —
                    # re-resolve and re-forward the ORIGINAL payload;
                    # bounded, then the refusal relays and the client's
                    # resubmit machinery owns the retry
                    entry["attempts"] += 1
                    telemetry.count("router.stale_reforwards")
                    if entry["attempts"] <= MAX_STALE_REFORWARDS:
                        await asyncio.sleep(0.02 * entry["attempts"])
                        await self.router._forward(
                            self, entry["family"], rid, entry["raw"],
                            entry["op"])
                        return
                elif (entry is not None and entry["op"] == "stream_open"
                        and obj.get("ok") and obj.get("stream")):
                    # learn the minted stream id's family so chunk /
                    # commit frames for it route sticky
                    self.router._learn_stream(str(obj["stream"]),
                                              entry["family"])
        if entry is not None:
            self.pending.pop(rid, None)
        await self.relay(payload)


class _GateTimeout(RuntimeError):
    pass


class FleetRouter:
    """See the module docstring.  ``hosts`` maps a label to a serving
    (host, port); ``families`` maps a family key to its session names
    (every host must serve the same session set — the router only ever
    re-homes families between identically-provisioned hosts);
    ``profiles`` maps stream-profile names to session names (a bare
    session name needs no entry).  ``gateway`` is the federation gateway
    whose ``host_down:*`` deadman alerts drive handoff."""

    def __init__(self, hosts: dict, families: dict, *,
                 profiles: dict | None = None,
                 gateway: "fleet_mod.FleetGateway | None" = None,
                 host: str = "127.0.0.1", port: int = 0,
                 control_interval_s: float = 0.05,
                 reassert_interval_s: float = 1.0,
                 gate_timeout_s: float = 30.0,
                 control_timeout_s: float = 5.0,
                 handoff_push_attempts: int = 1000):
        self.hosts = {str(lb): (str(a[0]), int(a[1]))
                      for lb, a in dict(hosts).items()}
        if not self.hosts:
            raise ValueError("FleetRouter needs at least one host")
        self.families = {str(f): sorted(str(s) for s in names)
                         for f, names in dict(families).items()}
        self.profiles = {str(k): str(v)
                         for k, v in dict(profiles or {}).items()}
        self.gateway = gateway
        self.host = host
        self.port = int(port)
        self.control_interval_s = float(control_interval_s)
        self.reassert_interval_s = float(reassert_interval_s)
        self.gate_timeout_s = float(gate_timeout_s)
        self.control_timeout_s = float(control_timeout_s)
        self.handoff_push_attempts = int(handoff_push_attempts)

        self._ring = HashRing(self.hosts)
        self._lock = threading.Lock()
        self._placement: dict = {}
        for fam in sorted(self.families):
            order = self._ring.order(fam)
            self._placement[fam] = {
                "owner": order[0],
                "successor": order[1] if len(order) > 1 else None,
                "epoch": 1}
        self._session_family: dict = {}
        for fam, names in self.families.items():
            for name in names:
                self._session_family[name] = fam
        self._sid_family: dict = {}
        self._down: set = set()
        # per-family admission gate: set = open; the control thread
        # closes it for the duration of a handoff so in-flight frames
        # wait instead of racing the ownership change
        self._gates = {fam: asyncio.Event() for fam in self.families}
        for ev in self._gates.values():
            ev.set()
        # per-source replication state: the export watermark already
        # fetched, and per-target buffered deltas not yet pushed
        self._repl = {label: {"since": 0, "pending": {}}
                      for label in self.hosts}
        self._handoffs: dict = {}
        self._handoff_durs: list = []
        self._loop: "asyncio.AbstractEventLoop | None" = None
        self._server: "asyncio.AbstractServer | None" = None
        self._conns: set = set()
        self._stop = threading.Event()
        self._control_thread: "threading.Thread | None" = None
        self._last_reassert = 0.0

    # ------------------------------------------------------------------
    # data plane (asyncio)
    # ------------------------------------------------------------------
    async def _start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._handle_client, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def _handle_client(self, reader, writer) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conns.add(task)
            task.add_done_callback(self._conns.discard)
        conn = _RouterConn(self, writer, asyncio.Lock())
        try:
            while True:
                try:
                    payload = await read_frame(reader)
                except ValueError as exc:
                    await conn.write_local({"ok": False,
                                            "error": f"bad frame: {exc}"})
                    break
                if payload is None:
                    break
                telemetry.count("router.bytes_rx",
                                len(payload) + HEADER.size)
                hdr = _peek_header(payload)
                if hdr is None:
                    await conn.write_local({
                        "ok": False,
                        "error": "bad frame: the router could not parse "
                                 "the payload header"})
                    continue
                op = hdr.get("op")
                if op == "hello":
                    await conn.write_local(self._hello(hdr))
                    continue
                if op == "ping":
                    await conn.write_local({
                        "ok": True, "pong": True, "router": True,
                        "sessions": self._all_sessions(),
                        "draining": False})
                    continue
                fam = self._route_family(hdr)
                if fam is None:
                    await conn.write_local(self._unroutable(hdr))
                    continue
                rid = hdr.get("id")
                if not isinstance(rid, str) or not rid:
                    await conn.write_local({
                        "ok": False,
                        "error": f"the router needs a request id on op "
                                 f"{op!r} to match its response"})
                    continue
                try:
                    await self._forward(conn, fam, rid, payload, op)
                except _GateTimeout:
                    telemetry.count("router.gate_timeouts")
                    await conn.write_local({
                        "id": rid, "ok": False,
                        "error": f"family {fam} unavailable: its handoff "
                                 "did not complete in time"})
                except (ConnectionError, faultinject.InjectedFault):
                    # backend unreachable (or injected routing death):
                    # die like a transport — the client reconnects and
                    # resubmits, deduped by the scheduler journal
                    break
        finally:
            for lk in list(conn.links.values()):
                await lk.close()
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass

    def _hello(self, hdr: dict) -> dict:
        offered = hdr.get("codecs")
        if not isinstance(offered, (list, tuple)):
            offered = [WIRE_CODEC_JSON]
        usable = [int(c) for c in offered
                  if isinstance(c, (int, float)) and int(c) in WIRE_CODECS]
        codec = max(usable, default=WIRE_CODEC_JSON)
        return {"ok": True, "hello": True, "router": True, "codec": codec,
                "codecs": list(WIRE_CODECS), "streams": True,
                "sessions": self._all_sessions(), "draining": False}

    def _all_sessions(self) -> list:
        return sorted(self._session_family)

    def _route_family(self, hdr: dict) -> "str | None":
        op = hdr.get("op")
        if op == "decode":
            return self._session_family.get(str(hdr.get("session")))
        if op == "stream_open":
            name = str(hdr.get("profile") or hdr.get("session") or "")
            return self._session_family.get(self.profiles.get(name, name))
        if op in ("stream_chunk", "stream_commit"):
            return self._sid_family.get(str(hdr.get("stream")))
        return None

    def _unroutable(self, hdr: dict) -> dict:
        op = hdr.get("op")
        if op in ("stream_chunk", "stream_commit"):
            sid = hdr.get("stream")
            return {"id": hdr.get("id"), "ok": False, "stream": sid,
                    "stream_unknown": True,
                    "error": f"unknown stream {sid!r} (shed, closed, or "
                             "never opened through this router)"}
        return {"id": hdr.get("id"), "ok": False,
                "error": f"the router cannot place op {op!r}: no "
                         "configured family serves it"}

    async def _forward(self, conn: _RouterConn, fam: str, rid: str,
                       payload: bytes, op) -> None:
        gate = self._gates.get(fam)
        if gate is not None and not gate.is_set():
            telemetry.count("router.gate_waits")
            try:
                await asyncio.wait_for(gate.wait(),
                                       timeout=self.gate_timeout_s)
            except asyncio.TimeoutError:
                raise _GateTimeout(fam) from None
        with self._lock:
            place = dict(self._placement[fam])
        epoch = int(place["epoch"])
        # routing chaos (ISSUE 18): under a ``router_partition`` fault
        # THIS frame forwards with a deliberately stale epoch, as a
        # partitioned router would — the owner's fence must refuse it
        # (``route_stale``) and the re-forward path must recover
        stale_marks: list = []
        faultinject.site("router_route",
                         actions={"router_partition": stale_marks.append})
        if stale_marks:
            epoch = max(0, epoch - 1)
            telemetry.count("router.partition_injected")
        link = await conn.link(place["owner"])
        conn.pending[rid] = {"raw": payload, "family": fam, "op": op,
                             "attempts": conn.pending.get(rid, {})
                             .get("attempts", 0)}
        await link.send(encode_routed_payload(fam, epoch, payload))
        telemetry.count("router.requests_routed")

    def _learn_stream(self, sid: str, fam: str) -> None:
        with self._lock:
            self._sid_family[sid] = fam

    # ------------------------------------------------------------------
    # control plane (daemon thread)
    # ------------------------------------------------------------------
    def _control(self, label: str) -> ControlClient:
        return ControlClient(self.hosts[label],
                             timeout_s=self.control_timeout_s)

    def start_control(self) -> None:
        if self._control_thread is not None:
            return
        # broadcast the initial placement BEFORE serving control ticks:
        # un-adopted families are refused by every host's fence
        self._assert_placement()
        self._stop.clear()
        t = threading.Thread(target=self._control_loop,
                             name="qldpc-fleet-router-ctl", daemon=True)
        self._control_thread = t
        t.start()

    def _control_loop(self) -> None:
        while not self._stop.wait(self.control_interval_s):
            try:
                self.control_once()
            except Exception:  # noqa: BLE001 — the loop never dies
                telemetry.count("router.control_errors")

    def control_once(self, now=None) -> None:
        """One control tick: deadman-driven handoffs, replication
        fetch/push over live hosts, periodic placement re-assert."""
        now = time.monotonic() if now is None else now
        if self.gateway is not None:
            for name in self.gateway.alerts.firing():
                if not name.startswith("host_down:"):
                    continue
                label = name.split(":", 1)[1]
                if label in self.hosts and label not in self._down:
                    self._handle_host_down(label)
        for label in sorted(self.hosts):
            if label in self._down:
                continue
            self._fetch_delta(label)
            self._push_pending(label)
        if now - self._last_reassert >= self.reassert_interval_s:
            self._last_reassert = now
            self._assert_placement()

    def _assert_placement(self) -> None:
        """Idempotent epoch broadcast: the owner adopts (own=True), every
        other live host is fenced (own=False).  Re-asserted periodically
        so a host returning from a partition re-learns the current fence
        before any stale frame could dispatch on it."""
        with self._lock:
            placement = {f: dict(p) for f, p in self._placement.items()}
            down = set(self._down)
        for fam in sorted(placement):
            place = placement[fam]
            for label in sorted(self.hosts):
                if label in down:
                    continue
                own = label == place["owner"]
                try:
                    self._control(label).call({
                        "op": "family_adopt",
                        "id": f"adopt-{fam}-{place['epoch']}-{label}",
                        "family": fam, "epoch": int(place["epoch"]),
                        "own": own,
                        "sessions": (self.families.get(fam, [])
                                     if own else [])})
                except Exception:  # noqa: BLE001 — re-asserted next round
                    telemetry.count("router.adopt_errors")

    def _fetch_delta(self, label: str) -> bool:
        """Eagerly pull ``label``'s journal delta past our watermark and
        buffer it per successor host.  Fetch is separate from push on
        purpose: a ``journal_lag`` fault fails only the PUSH, so fetched
        entries survive the source host's death in our buffer."""
        st = self._repl[label]
        try:
            rep = self._control(label).call({
                "op": "journal_export",
                "id": f"exp-{label}-{st['since']}",
                "since": int(st["since"])})
        except Exception:  # noqa: BLE001 — the host may simply be gone
            telemetry.count("router.replication_fetch_errors")
            return False
        if not rep.get("ok"):
            telemetry.count("router.replication_fetch_errors")
            return False
        st["since"] = max(int(st["since"]), int(rep.get("watermark", 0)))
        with self._lock:
            placement = {f: dict(p) for f, p in self._placement.items()}
        for entry in rep.get("entries", ()):
            key = entry.get("key") or ()
            fam = (self._session_family.get(str(key[1]))
                   if len(key) == 3 else None)
            target = (placement.get(fam, {}).get("successor")
                      if fam else None)
            if target is None or target in self._down:
                continue
            bucket = st["pending"].setdefault(target, _new_bucket())
            bucket["entries"].append(entry)
            bucket["watermark"] = max(bucket["watermark"],
                                      int(entry.get("seq", 0)))
        for state in rep.get("streams", ()):
            sid = state.get("stream")
            name = str(state.get("profile") or "")
            fam = self._session_family.get(self.profiles.get(name, name))
            target = (placement.get(fam, {}).get("successor")
                      if fam else None)
            if sid is None or fam is None:
                continue
            self._learn_stream(str(sid), fam)
            if target is None or target in self._down:
                continue
            bucket = st["pending"].setdefault(target, _new_bucket())
            # full state each export: the newest snapshot wins
            bucket["streams"][str(sid)] = state
        # warm-program manifests (ISSUE 20): forward each session's warm
        # (bucket, sharded) set to the family's successor so it pre-loads
        # the programs from the persistent cache BEFORE any handoff.
        # Deduped per (target, session, manifest) — the steady-state loop
        # re-exports every tick, but an unchanged manifest is not news.
        pushed = st.setdefault("prog_pushed", {})
        for name, keys in (rep.get("programs") or {}).items():
            fam = self._session_family.get(str(name))
            target = (placement.get(fam, {}).get("successor")
                      if fam else None)
            if target is None or target in self._down:
                continue
            sig = repr(keys)
            if pushed.get((target, str(name))) == sig:
                continue
            bucket = st["pending"].setdefault(target, _new_bucket())
            bucket["programs"][str(name)] = list(keys)
        return True

    def _push_pending(self, label: str) -> None:
        st = self._repl[label]
        for target in sorted(st["pending"]):
            bucket = st["pending"][target]
            if (not bucket["entries"] and not bucket["streams"]
                    and not bucket.get("programs")):
                continue
            if target in self._down:
                bucket["entries"].clear()
                bucket["streams"].clear()
                bucket.get("programs", {}).clear()
                continue
            try:
                self._push_delta(label, target, bucket)
            except Exception:  # noqa: BLE001 — buffered, retried next tick
                telemetry.count("router.replication_errors")

    def _push_delta(self, source: str, target: str, bucket: dict) -> None:
        """One replication push: the buffered delta from ``source``'s
        journal into ``target``.  Chaos (``journal_lag``) fails exactly
        here — the fetched delta stays buffered and the successor's
        watermark lags, which a handoff must then catch up on."""
        faultinject.site("router_replicate")
        programs = {n: list(k)
                    for n, k in bucket.get("programs", {}).items()}
        snapshot = {"watermark": int(bucket["watermark"]),
                    "entries": list(bucket["entries"]),
                    "streams": [dict(s)
                                for s in bucket["streams"].values()],
                    "programs": programs}
        rep = self._control(target).call({
            "op": "journal_import",
            "id": f"imp-{source}-{target}-{bucket['watermark']}",
            "snapshot": snapshot})
        if not rep.get("ok"):
            raise RuntimeError(
                f"journal_import on {target!r} refused: {rep.get('error')}")
        bucket["entries"].clear()
        bucket["streams"].clear()
        bucket.get("programs", {}).clear()
        if programs:
            # remember what landed so the steady-state re-export doesn't
            # re-push an unchanged manifest every tick
            pushed = self._repl[source].setdefault("prog_pushed", {})
            for n, k in programs.items():
                pushed[(target, n)] = repr(k)
            telemetry.count("router.program_pushes")
        telemetry.count("router.replication_pushes")

    # ------------------------------------------------------------------
    # handoff
    # ------------------------------------------------------------------
    def _set_gate(self, fam: str, open_: bool) -> None:
        ev = self._gates.get(fam)
        if ev is None:
            return
        loop = self._loop
        if loop is None or loop.is_closed():
            (ev.set if open_ else ev.clear)()
            return
        loop.call_soon_threadsafe(ev.set if open_ else ev.clear)

    def _handle_host_down(self, label: str) -> None:
        """The deadman fired for ``label``: gate its families, flush the
        buffered journal delta to each successor (BLOCKING until the
        watermark catches up — a lagging journal must never hand off
        stale), promote ownership at epoch+1, re-open the gates."""
        t0 = time.monotonic()
        with self._lock:
            self._down.add(label)
            fams = sorted(f for f, p in self._placement.items()
                          if p["owner"] == label)
        for fam in fams:
            self._set_gate(fam, False)
        telemetry.count("router.hosts_down")
        telemetry.event("scale_event", action="fleet_host_down",
                        target=label, reason="deadman")
        # last best-effort pull (usually fails — the host is dead; what
        # matters is everything the steady-state loop already fetched)
        self._fetch_delta(label)
        st = self._repl[label]
        for target in sorted(st["pending"]):
            bucket = st["pending"][target]
            if target in self._down:
                bucket["entries"].clear()
                bucket["streams"].clear()
                bucket.get("programs", {}).clear()
                continue
            attempts = 0
            while (bucket["entries"] or bucket["streams"]
                   or bucket.get("programs")):
                try:
                    self._push_delta(label, target, bucket)
                except Exception:  # noqa: BLE001
                    telemetry.count("router.replication_errors")
                    attempts += 1
                    if attempts >= self.handoff_push_attempts:
                        # give up loudly: the successor serves without
                        # this delta (fresh decodes stay deterministic,
                        # but replay coverage is lost) — counted so the
                        # acceptance gate can refuse
                        telemetry.count("router.handoff_drops")
                        bucket["entries"].clear()
                        bucket["streams"].clear()
                        bucket.get("programs", {}).clear()
                        break
                    # blocking here IS the contract: the handoff must not
                    # open the successor past a lagging journal
                    resilience.sleep_for(0.01)
        for fam in fams:
            self._promote(fam, reason=f"host_down:{label}")
            self._set_gate(fam, True)
        dur = time.monotonic() - t0
        telemetry.observe("router.handoff_s", dur)
        with self._lock:
            self._handoff_durs.append(dur)

    def _promote(self, fam: str, reason: str) -> bool:
        """Move ``fam``'s ownership to its successor at epoch+1: adopt on
        the new owner (with the session manifest — the adopt fails if the
        host cannot actually serve the family), then fence everyone
        else."""
        with self._lock:
            place = self._placement[fam]
            new_epoch = int(place["epoch"]) + 1
            old_owner = place["owner"]
            order = self._ring.order(fam, exclude=self._down)
            if not order:
                telemetry.count("router.no_successor")
                return False
            succ = place["successor"]
            new_owner = (succ if succ is not None
                         and succ not in self._down else order[0])
            rest = [lb for lb in order if lb != new_owner]
            new_successor = rest[0] if rest else None
        adopted = False
        # bounded adopt retry against a host that may still be binding
        for attempt in range(5):  # qldpc: ignore[R102]
            try:
                rep = self._control(new_owner).call({
                    "op": "family_adopt",
                    "id": f"promote-{fam}-{new_epoch}",
                    "family": fam, "epoch": new_epoch, "own": True,
                    "sessions": self.families.get(fam, [])})
                if rep.get("ok"):
                    adopted = True
                    break
            except Exception:  # noqa: BLE001
                pass
            telemetry.count("router.adopt_errors")
            resilience.sleep_for(0.05 * (attempt + 1))
        if not adopted:
            telemetry.count("router.promote_failures")
            return False
        with self._lock:
            self._placement[fam] = {"owner": new_owner,
                                    "successor": new_successor,
                                    "epoch": new_epoch}
            self._handoffs[fam] = {"t": time.time(), "epoch": new_epoch,
                                   "from": old_owner, "to": new_owner,
                                   "reason": reason}
        for label in sorted(self.hosts):
            if label == new_owner or label in self._down:
                continue
            try:
                self._control(label).call({
                    "op": "family_adopt",
                    "id": f"fence-{fam}-{new_epoch}-{label}",
                    "family": fam, "epoch": new_epoch, "own": False,
                    "sessions": []})
            except Exception:  # noqa: BLE001 — re-asserted next round
                telemetry.count("router.adopt_errors")
        telemetry.count("router.handoffs")
        telemetry.event("scale_event", action="fleet_handoff", target=fam,
                        to_value=new_epoch, reason=reason)
        return True

    def move_family(self, fam: str, target: str,
                    reason: str = "rebalance") -> bool:
        """Live rebalance: move ``fam`` from its (alive) owner to
        ``target`` — fence the source first (in-flight routed frames
        refuse with ``route_stale`` and re-forward after the move), ship
        a FULL journal snapshot, adopt, flip placement."""
        with self._lock:
            if fam not in self._placement or target not in self.hosts \
                    or target in self._down:
                return False
            place = dict(self._placement[fam])
        source = place["owner"]
        if source == target:
            return False
        new_epoch = int(place["epoch"]) + 1
        self._set_gate(fam, False)
        t0 = time.monotonic()
        try:
            try:
                self._control(source).call({
                    "op": "family_adopt",
                    "id": f"move-fence-{fam}-{new_epoch}",
                    "family": fam, "epoch": new_epoch, "own": False,
                    "sessions": []})
            except Exception:  # noqa: BLE001 — the fence re-asserts later
                telemetry.count("router.adopt_errors")
            # full snapshot (since=0): a move has a live source, so the
            # freshest state is one export away — no watermark dance
            try:
                rep = self._control(source).call({
                    "op": "journal_export",
                    "id": f"move-exp-{fam}-{new_epoch}", "since": 0})
            except Exception:  # noqa: BLE001
                rep = {"ok": False}
            if rep.get("ok"):
                names = set(self.families.get(fam, ()))
                entries = [e for e in rep.get("entries", ())
                           if len(e.get("key") or ()) == 3
                           and str(e["key"][1]) in names]
                streams = {}
                for state in rep.get("streams", ()):
                    pname = str(state.get("profile") or "")
                    if self.profiles.get(pname, pname) in names:
                        streams[str(state.get("stream"))] = state
                bucket = {"entries": entries, "streams": streams,
                          "watermark": max(
                              [int(e.get("seq", 0)) for e in entries],
                              default=0)}
                if bucket["entries"] or bucket["streams"]:
                    try:
                        self._push_delta(source, target, bucket)
                    except Exception:  # noqa: BLE001 — abort the move
                        telemetry.count("router.replication_errors")
                        try:
                            self._control(source).call({
                                "op": "family_adopt",
                                "id": f"move-abort-{fam}-{new_epoch}",
                                "family": fam, "epoch": new_epoch,
                                "own": True,
                                "sessions": self.families.get(fam, [])})
                        except Exception:  # noqa: BLE001
                            telemetry.count("router.adopt_errors")
                        return False
            try:
                rep = self._control(target).call({
                    "op": "family_adopt",
                    "id": f"move-adopt-{fam}-{new_epoch}",
                    "family": fam, "epoch": new_epoch, "own": True,
                    "sessions": self.families.get(fam, [])})
            except Exception:  # noqa: BLE001
                rep = {"ok": False}
            if not rep.get("ok"):
                telemetry.count("router.promote_failures")
                try:
                    self._control(source).call({
                        "op": "family_adopt",
                        "id": f"move-abort-{fam}-{new_epoch}",
                        "family": fam, "epoch": new_epoch, "own": True,
                        "sessions": self.families.get(fam, [])})
                except Exception:  # noqa: BLE001
                    telemetry.count("router.adopt_errors")
                return False
            with self._lock:
                order = self._ring.order(fam, exclude=self._down)
                rest = [lb for lb in order if lb != target]
                self._placement[fam] = {
                    "owner": target,
                    "successor": rest[0] if rest else None,
                    "epoch": new_epoch}
                self._handoffs[fam] = {"t": time.time(),
                                       "epoch": new_epoch,
                                       "from": source, "to": target,
                                       "reason": reason}
            dur = time.monotonic() - t0
            telemetry.observe("router.handoff_s", dur)
            with self._lock:
                self._handoff_durs.append(dur)
            telemetry.count("router.moves")
            telemetry.event("scale_event", action="fleet_move",
                            target=fam, to_value=new_epoch, reason=reason)
            return True
        finally:
            self._set_gate(fam, True)

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    @property
    def down(self) -> set:
        with self._lock:
            return set(self._down)

    def placement(self) -> dict:
        with self._lock:
            return {fam: dict(p) for fam, p in self._placement.items()}

    def handoff_report(self, now=None) -> dict:
        now = time.time() if now is None else now
        with self._lock:
            return {fam: {"age_s": round(now - h["t"], 3),
                          "epoch": h["epoch"], "from": h["from"],
                          "to": h["to"], "reason": h["reason"]}
                    for fam, h in self._handoffs.items()}

    def handoff_durations(self) -> list:
        with self._lock:
            return list(self._handoff_durs)

    # ------------------------------------------------------------------
    async def _shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
        for conn in list(self._conns):
            conn.cancel()
        if self._conns:
            await asyncio.gather(*list(self._conns),
                                 return_exceptions=True)
        if self._server is not None:
            await self._server.wait_closed()

    def stop_control(self, timeout: float = 10.0) -> None:
        self._stop.set()
        t = self._control_thread
        if t is not None:
            t.join(timeout)
        self._control_thread = None


class RouterHandle:
    """A FleetRouter's data plane on its own event-loop thread, plus its
    control loop — stopped together."""

    def __init__(self, router: FleetRouter, loop, thread):
        self.router = router
        self._loop = loop
        self._thread = thread

    @property
    def address(self) -> tuple:
        return (self.router.host, self.router.port)

    def stop(self, timeout: float = 15.0) -> None:
        self.router.stop_control(timeout)
        try:
            asyncio.run_coroutine_threadsafe(
                self.router._shutdown(), self._loop).result(timeout)
        finally:
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=timeout)


def start_router_thread(router: FleetRouter, *,
                        control: bool = True) -> RouterHandle:
    """Start the router's data plane on a daemon thread (and, with
    ``control``, broadcast the initial placement and start the control
    loop); returns once it accepts."""
    loop, thread = ops.spawn_server_loop(router._start,
                                         "qldpc-fleet-router",
                                         "fleet router")
    if control:
        router.start_control()
    return RouterHandle(router, loop, thread)


class RouterFleetServer(fleet_mod.FleetServer):
    """The fleet ops face with the router's state folded into /varz:
    the placement table (family -> owner/successor/epoch) and the
    last-handoff ages — what ``telemetry_report.py --fleet`` renders."""

    def __init__(self, router: FleetRouter,
                 gateway: "fleet_mod.FleetGateway",
                 host: str = "127.0.0.1", port: int = 0):
        super().__init__(gateway, host=host, port=port)
        self.router = router

    def varz(self) -> dict:
        body = super().varz()
        body["placement"] = self.router.placement()
        body["handoffs"] = self.router.handoff_report()
        body["down_hosts"] = sorted(self.router.down)
        return body


def start_router_ops_thread(router: FleetRouter, gateway=None,
                            host: str = "127.0.0.1", port: int = 0, *,
                            scrape: bool = False) -> "fleet_mod.FleetHandle":
    """Serve the router-aware fleet ops view on a daemon thread."""
    gw = gateway if gateway is not None else router.gateway
    if gw is None:
        raise ValueError("start_router_ops_thread needs a FleetGateway")
    server = RouterFleetServer(router, gw, host=host, port=port)
    loop, thread = ops.spawn_server_loop(server.start, "qldpc-router-ops",
                                         "router ops")
    if scrape:
        gw.start()
    return fleet_mod.FleetHandle(server, loop, thread)


class FleetScaler:
    """Fleet-level scaling: drives each host's AutoScaler (batch-target
    resize, mesh shard/retire — the per-host control laws stay where they
    are), and rebalances placement off the gateway's merged load signal —
    when the hottest host's queue depth exceeds the coldest's by
    ``rebalance_gap`` and the cooldown passed, the smallest family moves
    (live, via :meth:`FleetRouter.move_family`)."""

    def __init__(self, router: FleetRouter, gateway=None,
                 scalers: dict | None = None, *,
                 rebalance_gap: int = 64, cooldown_s: float = 5.0):
        self.router = router
        self.gateway = gateway if gateway is not None else router.gateway
        self.scalers = dict(scalers or {})
        self.rebalance_gap = int(rebalance_gap)
        self.cooldown_s = float(cooldown_s)
        self._last_move: "float | None" = None

    def evaluate_once(self, now=None) -> list:
        now = time.monotonic() if now is None else now
        down = self.router.down
        actions: list = []
        for label in sorted(self.scalers):
            if label in down:
                continue
            for act in (self.scalers[label].evaluate_once() or ()):
                actions.append({"host": label, "action": act})
        if self.gateway is None:
            return actions
        loads = {label: depth
                 for label, depth in self.gateway.host_loads().items()
                 if depth is not None and label not in down
                 and label in self.router.hosts}
        if len(loads) < 2:
            return actions
        hot = max(sorted(loads), key=lambda lb: loads[lb])
        cold = min(sorted(loads), key=lambda lb: loads[lb])
        gap = loads[hot] - loads[cold]
        cooled = (self._last_move is None
                  or now - self._last_move >= self.cooldown_s)
        if hot != cold and gap >= self.rebalance_gap and cooled:
            placement = self.router.placement()
            owned = sorted(
                (fam for fam, p in placement.items()
                 if p["owner"] == hot),
                key=lambda f: (len(self.router.families.get(f, ())), f))
            if owned and self.router.move_family(
                    owned[0], cold, reason=f"rebalance:{hot}->{cold}"):
                self._last_move = now
                actions.append({"host": hot, "action": "fleet_move",
                                "family": owned[0], "to": cold,
                                "gap": int(gap)})
        return actions


class LocalFleet:
    """An N-host in-process serving fleet behind one router: per-host
    ContinuousBatcher + DecodeServer + ops plane, one FleetGateway (fast
    scrape/deadman intervals), one FleetRouter.  The harness for the
    fleet chaos acceptance tests and ``bench.py fleet``.

    ``session_factory()`` builds one host's ``{name: DecodeSession}``
    (called once per host — every host serves the same session set);
    ``stream_profiles_factory()`` likewise for stream profiles.  Family
    keys derive from each session's ``bucket_family`` digest, so co-fused
    sessions always land on one host."""

    def __init__(self, session_factory, *, n_hosts: int = 2,
                 stream_profiles_factory=None,
                 batcher_kwargs: dict | None = None,
                 interval_s: float = 0.05, down_after_s: float = 0.25,
                 control_interval_s: float = 0.02,
                 warm: bool = False):
        from .scheduler import ContinuousBatcher
        from .server import start_server_thread
        from .session import family_digest

        self.labels = [f"h{i}" for i in range(int(n_hosts))]
        bkw = dict(batcher_kwargs or {})
        bkw.setdefault("max_batch_shots", 64)
        bkw.setdefault("max_wait_s", 0.002)
        self.sessions: dict = {}
        self.batchers: dict = {}
        self.server_handles: dict = {}
        self.ops_handles: dict = {}
        self._killed: set = set()
        self._kill_lock = threading.Lock()
        families: dict = {}
        profiles: dict = {}
        for label in self.labels:
            sessions = dict(session_factory())
            profs = (dict(stream_profiles_factory())
                     if stream_profiles_factory is not None else None)
            if warm:
                for sess in sessions.values():
                    sess.warm()
            self.sessions[label] = sessions
            bat = ContinuousBatcher(sessions, **bkw)
            self.batchers[label] = bat
            self.server_handles[label] = start_server_thread(
                bat, stream_profiles=profs)
            self.ops_handles[label] = ops.start_ops_thread(batcher=bat)
            if label == self.labels[0]:
                for name in sorted(sessions):
                    fam = f"fam-{family_digest(sessions[name].family)}"
                    families.setdefault(fam, []).append(name)
                if profs:
                    profiles = {pname: prof.session
                                for pname, prof in profs.items()}
        targets = {label: "http://{}:{}".format(*h.address)
                   for label, h in self.ops_handles.items()}
        self.gateway = fleet_mod.FleetGateway(
            targets, interval_s=interval_s, down_after_s=down_after_s)
        self.router = FleetRouter(
            hosts={lb: self.server_handles[lb].address
                   for lb in self.labels},
            families=families, profiles=profiles, gateway=self.gateway,
            control_interval_s=control_interval_s)
        self.router_handle = start_router_thread(self.router)
        self.ops_handle = start_router_ops_thread(
            self.router, self.gateway, scrape=True)

    @property
    def address(self) -> tuple:
        return self.router_handle.address

    # ------------------------------------------------------------------
    def chaos_tick(self) -> None:
        """Storm workers call this between requests; under a
        ``host_kill`` plan the matched hit kills the CURRENT owner of the
        first (sorted) family — deterministic given the seeded plan.  A
        fault carrying ``target`` aims instead: a host label kills that
        host, a family key kills its current owner."""
        faultinject.site("fleet_host_tick",
                         actions={"host_kill": self._enact_host_kill})

    def _enact_host_kill(self, fault) -> None:
        target = getattr(fault, "target", "") or ""
        if target in self.labels:
            self.kill(target)
            return
        placement = self.router.placement()
        fam = target if target in placement else sorted(placement)[0]
        self.kill(placement[fam]["owner"])

    def kill(self, label: str) -> bool:
        """Hard host death: the server's tasks are cancelled before the
        batcher closes (clients see pure transport death), then the ops
        plane stops so the gateway's scrapes fail and the ``host_down``
        deadman fires — the ONLY trigger for handoff."""
        with self._kill_lock:
            if label in self._killed:
                return False
            self._killed.add(label)
        self.server_handles[label].kill()
        self.ops_handles[label].stop()
        return True

    def stop(self) -> None:
        try:
            self.router_handle.stop()
        finally:
            try:
                self.ops_handle.stop()
            finally:
                for label in self.labels:
                    with self._kill_lock:
                        if label in self._killed:
                            continue
                    try:
                        self.ops_handles[label].stop()
                    except Exception:  # noqa: BLE001
                        pass
                    try:
                        self.server_handles[label].stop(drain=True)
                    except Exception:  # noqa: BLE001
                        pass
