"""Cross-host federation gateway (ISSUE 17 tentpole, part 3).

One serving host exposes /metrics /healthz /varz /tracez /alertz through
``ops.OpsServer``; a fleet needs those surfaces ONCE, not N times.  The
:class:`FleetGateway` scrapes every configured host's ops endpoint on an
interval, merges what it finds, and re-serves the fleet view on the same
stdlib-asyncio HTTP shape:

  * ``/metrics`` — merged Prometheus exposition: counter totals summed
    **bit-exactly** (integer sums of integer samples) and histogram bucket
    vectors added element-wise when boundaries agree (the bucket-boundary
    registry in utils.telemetry makes that the common case — a boundary
    mismatch skips the merge and is counted, never fudged), each with
    per-host labeled samples next to the unlabeled fleet total; gauges are
    inherently per-host (a queue depth does not sum) so they appear ONLY
    host-labeled, staleness stamps intact.
  * ``/healthz`` — per-host up/down + each host's own ok verdict, and an
    aggregate ``ok`` that is true only when every host is up and healthy.
  * ``/alertz`` — the union of every host's active/resolved alerts, each
    tagged with its host label, plus the gateway's own rules: host-down is
    itself an alert via the **deadman** kind (a host's successful-scrape
    heartbeat stops moving -> ``host_down:<label>`` fires).

Scraping rides ``/varz`` (the JSON snapshot) rather than parsing the text
exposition: merges then operate on exact integers, not rendered floats.
Host liveness heartbeats are fed into the gateway's own
:class:`utils.timeseries.SeriesStore` as synthetic counters, so the
deadman machinery is EXACTLY the one the local alert engine uses — same
store, same rule class, same transition events — and works with an
injectable clock for deterministic tests.
"""
from __future__ import annotations

import json
import threading
import time
import urllib.request

from ..utils import telemetry, timeseries
from . import ops

__all__ = [
    "FleetGateway", "FleetServer", "FleetHandle", "start_fleet_thread",
    "merge_snapshots",
]

DEFAULT_SCRAPE_INTERVAL_S = 5.0
DEFAULT_TIMEOUT_S = 5.0


def merge_snapshots(per_host: dict) -> dict:
    """Merge {host_label: registry-snapshot} into one fleet snapshot.

    Counters sum bit-exactly; histograms add bucket vectors + sum/count
    when every host agrees on boundaries (mismatches leave the metric
    unmerged, reported in ``skipped``); gauges never merge.  Returns
    ``{"merged": {name: metric}, "gauges": {name: {host: metric}},
    "skipped": [name, ...]}``.
    """
    merged: dict = {}
    gauges: dict = {}
    skipped: list = []
    for host in sorted(per_host):
        for name, m in per_host[host].items():
            kind = m.get("type")
            if kind == "gauge":
                gauges.setdefault(name, {})[host] = m
                continue
            if kind not in ("counter", "histogram") or name in skipped:
                continue
            cur = merged.get(name)
            if cur is None:
                if kind == "counter":
                    merged[name] = {"type": "counter", "value": m["value"]}
                else:
                    merged[name] = {
                        "type": "histogram",
                        "buckets": list(m["buckets"]),
                        "counts": list(m["counts"]),
                        "sum": m["sum"], "count": int(m["count"]),
                    }
                continue
            if cur["type"] != kind:
                skipped.append(name)
                merged.pop(name, None)
                continue
            if kind == "counter":
                cur["value"] += m["value"]
            else:
                if list(m["buckets"]) != cur["buckets"] or \
                        len(m["counts"]) != len(cur["counts"]):
                    skipped.append(name)
                    merged.pop(name, None)
                    continue
                cur["counts"] = [a + b for a, b in
                                 zip(cur["counts"], m["counts"])]
                cur["sum"] += m["sum"]
                cur["count"] += int(m["count"])
    return {"merged": merged, "gauges": gauges, "skipped": sorted(skipped)}


class FleetGateway:
    """Scrape N ops endpoints, merge, alert on host loss.

    ``targets`` maps a host label to an ops base URL
    (``{"a": "http://127.0.0.1:9001", ...}``).  ``scrape_once(now)`` is
    the synchronous unit tests drive with an injectable clock and a
    pluggable ``fetch`` (label, path) -> dict; ``start()`` runs it on a
    daemon thread (HealthProbe's ``Event.wait`` loop).  ``down_after_s``
    is the deadman window for the per-host heartbeat (default 3 scrape
    intervals).
    """

    def __init__(self, targets: dict, *,
                 interval_s: float = DEFAULT_SCRAPE_INTERVAL_S,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 down_after_s: float | None = None,
                 now=time.time, fetch=None):
        self.targets = {str(k): str(v).rstrip("/")
                        for k, v in dict(targets).items()}
        if not self.targets:
            raise ValueError("FleetGateway needs at least one target")
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.down_after_s = (3.0 * self.interval_s if down_after_s is None
                             else float(down_after_s))
        self._now = now
        self._fetch = fetch if fetch is not None else self._fetch_http
        self._lock = threading.Lock()
        # per-host scrape state: snap/health/alertz payloads + bookkeeping
        self._hosts: dict[str, dict] = {
            label: {"ok_scrapes": 0, "last_ok": None, "last_error": None,
                    "snap": {}, "healthz": None, "alertz": None}
            for label in self.targets}
        self.scrapes = 0
        self.t_started = now()
        # the gateway's OWN time-series + alert engine: one deadman rule
        # per host over its successful-scrape heartbeat
        self.store = timeseries.SeriesStore()
        self.alerts = ops.AlertEngine(store=self.store, now=now)
        for label in sorted(self.targets):
            self.alerts.add_rule(ops.AlertRule(
                name=f"host_down:{label}",
                metric=f"fleet.host.{label}.ok_scrapes",
                kind="deadman", window_s=self.down_after_s,
                severity="critical"))
        # merge loss is operator-visible, not just a /varz list (ISSUE 18
        # satellite): every scrape whose merge skipped metrics (type
        # conflict / histogram boundary mismatch) bumps a counter, and a
        # default rate rule pages while skips keep happening
        self._merge_skips = 0
        self.alerts.add_rule(ops.AlertRule(
            name="fleet_merge_skips", metric="fleet.merge_skips",
            kind="threshold", mode="rate", op=">", threshold=0.0,
            window_s=self.down_after_s, severity="warning"))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------------
    def _fetch_http(self, label: str, path: str) -> dict:
        url = self.targets[label] + path
        with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def scrape_once(self, now=None) -> dict:
        """One scrape round over every host; returns {label: up_bool}.
        The heartbeat counters are ingested and the host-down deadman
        rules evaluated at the SAME ``now``, so tests step time
        explicitly."""
        now = self._now() if now is None else now
        up: dict = {}
        for label in sorted(self.targets):
            state = self._hosts[label]
            try:
                varz = self._fetch(label, "/varz")
                healthz = self._fetch(label, "/healthz")
                alertz = self._fetch(label, "/alertz")
            except Exception as exc:  # host down IS the signal, not a bug
                up[label] = False
                with self._lock:
                    state["last_error"] = f"{type(exc).__name__}: {exc}"
                telemetry.count("fleet.scrape_errors")
                continue
            up[label] = True
            with self._lock:
                state["ok_scrapes"] += 1
                state["last_ok"] = now
                state["last_error"] = None
                state["snap"] = varz.get("metrics", {})
                state["healthz"] = healthz
                state["alertz"] = alertz
        with self._lock:
            self.scrapes += 1
            heartbeats = {
                f"fleet.host.{label}.ok_scrapes":
                    {"type": "counter",
                     "value": self._hosts[label]["ok_scrapes"]}
                for label in self.targets}
            per_host = {label: st["snap"] for label, st in
                        self._hosts.items() if st["snap"]}
        # count this round's merge skips (a skipped metric stays skipped
        # every round it conflicts — the rate rule fires for as long as
        # the conflict persists, which is exactly the operator signal)
        skips = len(merge_snapshots(per_host)["skipped"])
        if skips:
            telemetry.count("fleet.merge_skips", skips)
        with self._lock:
            self._merge_skips += skips
            heartbeats["fleet.merge_skips"] = {
                "type": "counter", "value": self._merge_skips}
        self.store.ingest(now, heartbeats)
        self.alerts.evaluate(now=now)
        telemetry.count("fleet.scrapes")
        telemetry.set_gauge("fleet.host_up", sum(up.values()))
        return up

    # ------------------------------------------------------------------
    def merged(self) -> dict:
        """The current merge (see :func:`merge_snapshots`) over the last
        successful snapshot of every host that has one."""
        with self._lock:
            per_host = {label: st["snap"] for label, st in
                        self._hosts.items() if st["snap"]}
        return merge_snapshots(per_host)

    def metrics_text(self) -> str:
        """Fleet Prometheus exposition: per family one HELP/TYPE, the
        unlabeled fleet total (counters/histograms), and per-host labeled
        samples (counters and gauges — gauges have no total)."""
        with self._lock:
            per_host = {label: dict(st["snap"]) for label, st in
                        self._hosts.items() if st["snap"]}
        fleet = merge_snapshots(per_host)
        pt = telemetry  # naming helpers live with the local exposition
        lines = []
        for name in sorted(set(fleet["merged"]) | set(fleet["gauges"])):
            pn = pt._prom_name(name)
            if name in fleet["merged"]:
                m = fleet["merged"][name]
                lines.append(f"# HELP {pn} "
                             f"{pt._prom_help(pt.metric_help(name))}")
                lines.append(f"# TYPE {pn} {m['type']}")
                if m["type"] == "counter":
                    lines.append(f"{pn} {pt._prom_num(m['value'])}")
                    for host in sorted(per_host):
                        hm = per_host[host].get(name)
                        if hm is not None:
                            lines.append(f'{pn}{{host="{host}"}} '
                                         f'{pt._prom_num(hm["value"])}')
                else:
                    acc = 0
                    for edge, c in zip(m["buckets"], m["counts"]):
                        acc += c
                        lines.append(f'{pn}_bucket{{le='
                                     f'"{pt._prom_num(edge)}"}} {acc}')
                    acc += m["counts"][-1]
                    lines.append(f'{pn}_bucket{{le="+Inf"}} {acc}')
                    lines.append(f"{pn}_sum {pt._prom_num(m['sum'])}")
                    lines.append(f"{pn}_count {m['count']}")
            else:
                lines.append(f"# HELP {pn} "
                             f"{pt._prom_help(pt.metric_help(name))}")
                lines.append(f"# TYPE {pn} gauge")
                for host, hm in sorted(fleet["gauges"][name].items()):
                    lines.append(f'{pn}{{host="{host}"}} '
                                 f'{pt._prom_num(hm["value"])}')
        return "\n".join(lines) + "\n"

    def healthz(self, now=None) -> dict:
        """Per-host up/down + aggregate.  A host is up when its heartbeat
        deadman is NOT firing and its own /healthz said ok."""
        now = self._now() if now is None else now
        firing = set(self.alerts.firing())
        hosts = {}
        ok = True
        n_up = 0
        with self._lock:
            for label, st in sorted(self._hosts.items()):
                host_up = f"host_down:{label}" not in firing \
                    and st["last_ok"] is not None
                host_ok = bool(st["healthz"] and st["healthz"].get("ok"))
                hosts[label] = {
                    "up": host_up, "ok": host_ok,
                    "last_ok_age_s": (None if st["last_ok"] is None
                                      else round(now - st["last_ok"], 3)),
                    "ok_scrapes": st["ok_scrapes"],
                    "error": st["last_error"],
                }
                n_up += bool(host_up)
                ok = ok and host_up and host_ok
        return {"ok": ok, "hosts": hosts, "up": n_up,
                "down": sorted(label for label, h in hosts.items()
                               if not h["up"]),
                "targets": len(self.targets),
                "uptime_s": round(now - self.t_started, 3)}

    def alertz(self, now=None) -> dict:
        """Fleet alert view: every host's active/resolved alerts tagged
        with its label, plus the gateway's own (host-down deadman)
        tagged ``host="fleet"``."""
        own = self.alerts.report(now=now)
        active = [dict(a, host="fleet") for a in own["active"]]
        resolved = [dict(r, host="fleet") for r in own["resolved"]]
        with self._lock:
            for label, st in sorted(self._hosts.items()):
                hz = st["alertz"]
                if not hz:
                    continue
                active.extend(dict(a, host=label)
                              for a in hz.get("active", ()))
                resolved.extend(dict(r, host=label)
                                for r in hz.get("resolved", ()))
        return {"active": active, "resolved": resolved,
                "hosts": sorted(self.targets), "scrapes": int(self.scrapes)}

    def host_loads(self) -> dict:
        """Per-host load signal for the fleet scaler: each host's last
        /healthz queue depth (None while a host has never been scraped or
        its healthz omitted one).  Reads the scrape cache only — never
        blocks on the network."""
        with self._lock:
            return {label: (st["healthz"] or {}).get("queue_depth")
                    for label, st in self._hosts.items()}

    def varz(self) -> dict:
        fleet = self.merged()
        with self._lock:
            merge_skips = int(self._merge_skips)
        return {"targets": dict(self.targets),
                "scrapes": int(self.scrapes),
                "merged": fleet["merged"],
                "gauges": fleet["gauges"],
                "merge_skipped": fleet["skipped"],
                "merge_skips": merge_skips}

    # -- daemon loop (Event.wait, no bare sleep) ------------------------
    def start(self) -> "FleetGateway":
        if self._thread is not None:
            return self
        self._stop.clear()
        t = threading.Thread(target=self._run, name="qldpc-fleet-gateway",
                             daemon=True)
        self._thread = t
        t.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 — the loop never dies
                telemetry.count("fleet.loop_errors")

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None


class FleetServer(ops.OpsServer):
    """The fleet HTTP face: same GET-only asyncio shape as the per-host
    ops plane, but every endpoint answers from the gateway's merged
    state.  ``/varz`` shows the merge itself (inputs + skips) so a
    boundary mismatch is visible, not silent."""

    def __init__(self, gateway: FleetGateway,
                 host: str = "127.0.0.1", port: int = 0):
        super().__init__(host=host, port=port)
        self.gateway = gateway

    def healthz(self) -> dict:
        return self.gateway.healthz()

    def varz(self) -> dict:
        return self.gateway.varz()

    def alertz(self) -> dict:
        return self.gateway.alertz()

    def _route(self, target: str) -> bytes:
        telemetry.count("fleet.ops.requests")
        path = target.split("?", 1)[0]
        try:
            if path == "/metrics":
                return ops._http_response(
                    200, self.gateway.metrics_text(),
                    content_type=telemetry.PROMETHEUS_CONTENT_TYPE)
            if path == "/healthz":
                body = self.healthz()
                return ops._http_response(
                    200 if body.get("ok") else 503,
                    json.dumps(body, sort_keys=True, default=str))
            if path == "/varz":
                return ops._http_response(200, json.dumps(
                    self.varz(), sort_keys=True, default=str))
            if path == "/alertz":
                return ops._http_response(200, json.dumps(
                    self.alertz(), sort_keys=True, default=str))
            return ops._http_response(404, json.dumps(
                {"error": f"unknown path {path!r}", "paths":
                 ["/metrics", "/healthz", "/varz", "/alertz"]}))
        except Exception as exc:  # noqa: BLE001 — an ops bug must answer
            return ops._http_response(500, json.dumps(
                {"error": f"{type(exc).__name__}: {exc}"}))


class FleetHandle(ops.OpsHandle):
    """A FleetServer + its gateway scrape loop, stopped together."""

    def __init__(self, server: FleetServer, loop, thread):
        super().__init__(server, loop, thread)
        self.gateway = server.gateway

    def stop(self, timeout: float = 10.0) -> None:
        self.gateway.stop(timeout)
        super().stop(timeout)


def start_fleet_thread(gateway: FleetGateway, host: str = "127.0.0.1",
                       port: int = 0, *, scrape: bool = True) -> FleetHandle:
    """Serve the fleet view on a daemon thread (and start the scrape loop
    unless ``scrape=False`` — tests drive ``scrape_once`` themselves)."""
    server = FleetServer(gateway, host=host, port=port)
    loop, thread = ops.spawn_server_loop(server.start, "qldpc-fleet-ops",
                                         "fleet gateway")
    if scrape:
        gateway.start()
    return FleetHandle(server, loop, thread)
