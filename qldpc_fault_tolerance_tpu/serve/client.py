"""Blocking decode-service client with pipelined submits, reconnect and
hedged resubmit.

A thin stdlib-socket counterpart to serve/server.py's protocol: ``submit``
sends a decode frame and returns a future immediately (responses stream
back in completion order and are matched by id on a background reader
thread), so a load generator keeps a window of requests in flight without
one connection per request.  ``decode`` is the submit+wait convenience.

Latency is measured CLIENT-side (submit to response-parsed), which is the
number a tail-latency SLO is actually about — it includes the wire, the
queue wait, the batch fill and the dispatch.

Tracing (ISSUE 11): construct with ``traced=True`` (or pass ``trace=`` per
submit) and every request mints a ``utils.tracing.TraceContext`` that
rides the optional wire field — the server records the full stage-span
tree under it and echoes the trace id back on ``ClientResult.trace_id``,
the key for the JSONL stream and ``/tracez``.

Self-healing transport (ISSUE 14):

  * a broken pipe is a PER-REQUEST transient error, never fatal to the
    client: a submit that hits a dead socket resolves ITS future with a
    ``ConnectionError`` (classified transient by utils.resilience) and
    the client stays usable — or, with ``reconnect=True``, the request
    simply rides the resubmit below;
  * ``reconnect=True`` — when the connection dies, the reader thread
    redials (bounded attempts, jittered backoff via the sanctioned
    ``resilience.sleep_for``) and RESUBMITS every unanswered request on
    the new connection with a fresh wire id and the SAME idempotency key
    (serve/wire.py ``IDEM_FIELD``), which the server's journal dedupes —
    a request whose response died on the wire is replayed from the
    answered cache, never decoded twice;
  * ``hedge_s=<seconds>`` — a request unanswered for that long is
    resubmitted on the live connection (same idempotency key, bounded
    ``max_hedges``); the server attaches the duplicate to the in-flight
    decode, so hedging bounds tail latency without duplicating work.

Idempotency keys are minted automatically whenever ``reconnect`` or
``hedge_s`` is enabled (or explicitly via ``idempotent=True``); a plain
client sends frames byte-identical to pre-ISSUE-14 builds.

Wire codec (ISSUE 15): ``codec="auto"`` (the default) negotiates the
packed binary codec via a ``hello`` at connect — syndromes ship as
gf2_packed lane words instead of JSON int matrices, corrections and
convergence come back the same way — and falls back to JSON against an
old server.  ``codec=1`` forces JSON (no hello, frames byte-identical to
pre-v2 builds); ``codec=2`` requires the packed codec.  Reconnects
renegotiate on the fresh socket.  ``serve.client.bytes_rx/tx`` count
framed bytes both ways.

Streaming decode (ISSUE 16): ``stream_open`` opens an overlap-commit
stream on the server, ``stream_step`` sends one window's detector
increment and blocks for its committed corrections, ``stream_commit``
queries the commit watermark (the resume handshake) or closes the
stream.  Stream responses resolve as RAW dicts (they are not decode
results), and a stream request is never auto-resubmitted: the step
helper retries the SAME seq itself — the server's commit-before-respond
ledger replays an already-committed seq from cache, so a retry can
never double-commit a window.
"""
from __future__ import annotations

import dataclasses
import itertools
import json
import os
import socket
import threading
import time
import uuid
from collections import deque
from concurrent.futures import Future

import numpy as np

from ..utils import resilience, telemetry, tracing
from .wire import (
    HEADER,
    IDEM_FIELD,
    MAX_FRAME_BYTES,
    TRACE_FIELD,
    WIRE_CODEC_JSON,
    WIRE_CODEC_PACKED,
    WireCodecError,
    decode_payload,
    encode_frame,
    encode_request_frame,
    encode_stream_chunk_frame,
)

__all__ = ["ClientResult", "DecodeClient"]


@dataclasses.dataclass
class ClientResult:
    corrections: np.ndarray          # (k, n) uint8
    converged: list | None
    latency_s: float                 # client-side: submit -> response parsed
    server_latency_ms: float | None  # scheduler-side, from the response
    request_id: str
    trace_id: str | None = None      # echoed by the server when traced


class _Inflight:
    """One logical request across its transmissions: the base frame (all
    fields but the wire id; None for clients that can never resend — no
    point retaining the payload), the future, and every wire id it has
    been sent under (reconnect resubmits and hedges mint fresh ones; the
    server matches responses to whichever transmission answered)."""

    __slots__ = ("future", "t0", "base", "rids", "last_tx", "hedges",
                 "resubmits", "raw")

    def __init__(self, base: dict, t0: float, raw: bool = False):
        self.future: Future = Future()
        self.t0 = t0
        self.base = base
        self.rids: set[str] = set()
        self.last_tx = t0
        self.hedges = 0
        self.resubmits = 0
        # raw requests (stream ops) resolve with the response DICT, not a
        # ClientResult, and are never auto-resubmitted or hedged (base is
        # None): stream seqs must only ever be retried by their caller
        self.raw = raw


class DecodeClient:
    def __init__(self, host: str, port: int, *, tenant: str = "default",
                 timeout: float = 60.0, traced: bool = False,
                 reconnect: bool = False,
                 max_reconnects: "int | None" = None,
                 reconnect_backoff_s: "float | None" = None,
                 hedge_s: float | None = None, max_hedges: int = 1,
                 idempotent: bool | None = None,
                 codec: "int | str" = "auto"):
        self.host, self.port = host, int(port)
        self.tenant = str(tenant)
        self.traced = bool(traced)
        self.timeout = float(timeout)
        # wire codec (ISSUE 15): "auto" negotiates the packed binary codec
        # via the hello op at connect and falls back to JSON against an
        # old server; 1 forces JSON (no hello — frames byte-identical to
        # pre-v2 builds); 2 requires the packed codec (raises when the
        # server can't speak it).  Renegotiated on every reconnect.
        if codec not in ("auto", WIRE_CODEC_JSON, WIRE_CODEC_PACKED):
            raise ValueError(f"codec must be 'auto', 1 or 2, got {codec!r}")
        self._codec_req = codec
        self.wire_codec = WIRE_CODEC_JSON
        self.reconnect = bool(reconnect)
        # dial/redial policy (ISSUE 18 satellite): env-tunable defaults
        # (an operator retunes a fleet's reconnect storm behavior without
        # touching code), explicit arguments win.  The delay schedule
        # itself comes from utils.resilience.RetryPolicy — the ONE backoff
        # implementation — capped at 2 s like the historical inline dial
        # loop, with no jitter so chaos tests stay deterministic.
        if max_reconnects is None:
            max_reconnects = int(os.environ.get(
                "QLDPC_CLIENT_RETRY_ATTEMPTS", "8"))
        if reconnect_backoff_s is None:
            reconnect_backoff_s = float(os.environ.get(
                "QLDPC_CLIENT_RETRY_BASE_S", "0.05"))
        self.max_reconnects = max(1, int(max_reconnects))
        self.reconnect_backoff_s = float(reconnect_backoff_s)
        self._dial_policy = resilience.RetryPolicy(
            max_attempts=self.max_reconnects,
            base_delay=self.reconnect_backoff_s, backoff=2.0,
            max_delay=2.0, jitter=0.0, reset_caches=False)
        self.hedge_s = None if hedge_s is None else float(hedge_s)
        self.max_hedges = max(0, int(max_hedges))
        # resubmits and hedges only dedupe server-side when requests carry
        # idempotency keys, so those modes imply them; a plain client
        # keeps its frames byte-identical to older builds
        self.idempotent = (bool(reconnect or hedge_s is not None)
                           if idempotent is None else bool(idempotent))
        self.reconnects = 0
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        # negotiate BEFORE the reader thread starts: the hello reply is
        # read synchronously off the fresh socket, so the pump never has
        # to disambiguate negotiation frames from responses
        self.wire_codec = self._negotiate(self._sock)
        self._wlock = threading.Lock()
        self._plock = threading.Lock()
        # wire id -> logical request (several ids may map to one request)
        self._reqs: dict[str, _Inflight] = {}
        # ping waiters queue FIFO (pongs come back in order): concurrent
        # pings from threads sharing one client each get their own future
        self._pongs: deque = deque()
        self._closed = False
        # set (under _plock, atomically with failing the outstanding
        # requests) when the transport is permanently gone — a submit
        # after that point must fail ITS future immediately instead of
        # registering work no reader will ever resolve
        self._dead = False
        self._stop = threading.Event()
        self._ids = itertools.count()
        self._prefix = uuid.uuid4().hex[:8]
        # idempotency keys key SERVER-side dedupe (scoped per tenant +
        # session there, but key collisions between a fleet's clients of
        # one tenant would still cross requests): full 128-bit uuid, not
        # the short wire-id prefix whose 32 bits birthday-collide at
        # fleet scale
        self._idem_prefix = uuid.uuid4().hex
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name="qldpc-serve-client")
        self._reader.start()
        self._hedger = None
        if self.hedge_s is not None and self.max_hedges > 0:
            self._hedger = threading.Thread(
                target=self._hedge_loop, daemon=True,
                name="qldpc-serve-client-hedge")
            self._hedger.start()

    # ------------------------------------------------------------------
    # wire codec negotiation (ISSUE 15)
    # ------------------------------------------------------------------
    @staticmethod
    def _read_exact_sync(sock, n: int) -> bytes:
        """Exactly ``n`` bytes off a blocking socket (negotiation only —
        the socket's timeout bounds the wait; EOF raises)."""
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("connection closed during codec "
                                      "negotiation")
            buf += chunk
        return buf

    def _negotiate(self, sock) -> int:
        """Hello handshake on a FRESH socket (constructor / reconnect,
        before the reader pumps it).  Returns the codec to send with.
        ``codec=1`` skips the handshake entirely; ``codec=2`` raises when
        the server can't speak the packed codec; ``"auto"`` falls back to
        JSON against an old server (which answers "unknown op")."""
        if self._codec_req == WIRE_CODEC_JSON:
            return WIRE_CODEC_JSON
        negotiated = WIRE_CODEC_JSON
        try:
            hello = encode_frame(
                {"op": "hello",
                 "codecs": [WIRE_CODEC_PACKED, WIRE_CODEC_JSON]})
            telemetry.count("serve.client.bytes_tx", len(hello))
            sock.sendall(hello)
            head = self._read_exact_sync(sock, HEADER.size)
            (length,) = HEADER.unpack(head)
            if length > MAX_FRAME_BYTES:
                raise ConnectionError(f"oversize hello reply ({length}B)")
            telemetry.count("serve.client.bytes_rx",
                            length + HEADER.size)
            msg = decode_payload(self._read_exact_sync(sock, length))
            if isinstance(msg, dict) and msg.get("hello") \
                    and int(msg.get("codec", WIRE_CODEC_JSON)) \
                    == WIRE_CODEC_PACKED:
                negotiated = WIRE_CODEC_PACKED
        except (OSError, ValueError, KeyError, json.JSONDecodeError,
                UnicodeDecodeError):
            # old server (unknown-op reply), torn wire or a socket that
            # died under the handshake: stay on JSON — a dead transport
            # must keep surfacing per-REQUEST (or via reconnect), exactly
            # as it did before v2, never as a constructor failure
            negotiated = WIRE_CODEC_JSON
        if self._codec_req == WIRE_CODEC_PACKED \
                and negotiated != WIRE_CODEC_PACKED:
            raise ValueError(
                "server does not speak wire codec 2 (packed binary); "
                "construct the client with codec='auto' or 1")
        telemetry.count(f"serve.client.codec.v{negotiated}_conns")
        telemetry.set_gauge("wire.codec_version", negotiated)
        return negotiated

    # ------------------------------------------------------------------
    def _send(self, obj) -> None:
        # encode under the SAME _wlock hold that sends: _reconnect swaps
        # (socket, wire_codec) atomically under it, and a frame encoded
        # with a stale codec must never land on a freshly renegotiated
        # connection (a packed frame on a JSON-only server kills the
        # whole pipelined connection)
        with self._wlock:
            op = obj.get("op")
            if op == "decode":
                frame = encode_request_frame(obj, self.wire_codec)
            elif op == "stream_chunk":
                frame = encode_stream_chunk_frame(obj, self.wire_codec)
            else:
                frame = encode_frame(obj)
            telemetry.count("serve.client.bytes_tx", len(frame))
            self._sock.sendall(frame)

    def _recv_exact(self, sock, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            try:
                chunk = sock.recv(n - len(buf))
            except socket.timeout:
                # idle is NOT disconnect: a low-traffic client must keep
                # its reader alive past the socket timeout (close() breaks
                # the loop via shutdown -> OSError below)
                if self._closed:
                    return None
                continue
            except (OSError, ValueError):
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    def _pump(self, sock) -> None:
        """Read frames off ONE socket until it dies."""
        while True:
            head = self._recv_exact(sock, HEADER.size)
            if head is None:
                return
            (length,) = HEADER.unpack(head)
            if length > MAX_FRAME_BYTES:
                return  # protocol corruption — reconnect or fail pending
            body = self._recv_exact(sock, length)
            if body is None:
                return
            telemetry.count("serve.client.bytes_rx",
                            len(body) + HEADER.size)
            try:
                msg = decode_payload(body)
            except WireCodecError as exc:
                # a malformed binary response fails ITS request (when the
                # header named one) — the reader and the rest of the
                # pipeline survive, like the malformed-JSON path below
                telemetry.count("serve.client.wire_errors")
                rid = exc.request_id
                if rid is not None:
                    with self._plock:
                        req = self._reqs.get(rid)
                    if req is not None:
                        self._fail_request(req, RuntimeError(
                            f"malformed decode response: {exc}"))
                continue
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue
            if not isinstance(msg, dict):
                continue
            if msg.get("pong"):
                with self._plock:
                    pong = self._pongs.popleft() if self._pongs else None
                if pong is not None:
                    pong.set_result(msg)
                continue
            rid = msg.get("id")
            with self._plock:
                req = self._reqs.get(rid)
                if req is not None:
                    # one answer resolves the LOGICAL request: retire
                    # every wire id it was transmitted under (a hedge's
                    # late second answer finds nothing and is dropped)
                    for r in req.rids:
                        self._reqs.pop(r, None)
            if req is None:
                continue
            fut, t0 = req.future, req.t0
            if fut.done():
                continue
            if req.raw:
                # stream ops resolve with the raw response dict — ok and
                # structured-error alike; the caller owns interpretation
                # (retry on "busy", resume on shed, fold corrections)
                fut.set_result(dict(msg))
                continue
            if msg.get("ok"):
                try:
                    result = ClientResult(
                        corrections=np.asarray(msg["corrections"],
                                               np.uint8),
                        converged=msg.get("converged"),
                        latency_s=time.perf_counter() - t0,
                        server_latency_ms=msg.get("latency_ms"),
                        request_id=str(rid),
                        trace_id=msg.get("trace_id"))
                except Exception as exc:  # noqa: BLE001 — reader survives
                    # a parseable-but-malformed response (version skew,
                    # corruption) fails ITS request; killing the reader
                    # here would skip the reconnect path AND the final
                    # drain, hanging every other outstanding future
                    fut.set_exception(RuntimeError(
                        f"malformed decode response: "
                        f"{type(exc).__name__}: {exc}"))
                    continue
                fut.set_result(result)
            else:
                fut.set_exception(
                    RuntimeError(msg.get("error", "decode failed")))

    def _logical_reqs(self) -> list:
        """Unique in-flight logical requests (several wire ids may map to
        one ``_Inflight``).  Call under ``_plock``."""
        return list({id(r): r for r in self._reqs.values()}.values())

    def _read_loop(self) -> None:
        while True:
            t_conn = time.perf_counter()
            try:
                self._pump(self._sock)
            except Exception:  # noqa: BLE001 — epilogue must always run
                # whatever killed the pump, the drain below (or the
                # reconnect) must still happen: a dead reader that never
                # set _dead would hang every outstanding future
                telemetry.count("serve.client.reader_errors")
            lifetime = time.perf_counter() - t_conn
            if self._closed or not self.reconnect:
                break
            # a connection that died almost immediately signals a
            # crash-looping server: back off BEFORE the first redial too,
            # or accept->die->redial->resubmit becomes a zero-sleep spin
            if not self._reconnect(fast_death=lifetime < 1.0):
                break
        # transport permanently gone: fail whatever is still outstanding.
        # _dead flips under the SAME lock hold that drains the table, so
        # a racing submit either lands in the drain or sees the flag
        with self._plock:
            self._dead = True
            reqs, self._reqs = self._reqs, {}
            pongs, self._pongs = list(self._pongs), deque()
        err = ConnectionError("decode-service connection closed")
        for req in {id(r): r for r in reqs.values()}.values():
            if not req.future.done():
                req.future.set_exception(err)
        for pong in pongs:
            if not pong.done():
                pong.set_exception(err)

    def _fail_request(self, req, exc: Exception) -> None:
        """Retire one logical request with an error: unregister every
        wire id and fail its future (used for unsendable frames — e.g. a
        payload over the frame cap, which no resend can ever fix)."""
        with self._plock:
            for r in list(req.rids):
                self._reqs.pop(r, None)
        if not req.future.done():
            req.future.set_exception(exc)

    # ------------------------------------------------------------------
    # reconnect + resubmit (the self-healing transport)
    # ------------------------------------------------------------------
    def _reconnect(self, fast_death: bool = False) -> bool:
        """Redial (bounded attempts, backoff) and resubmit every
        unanswered request on the fresh connection.  Returns True when a
        new connection is live.  ``fast_death`` (the previous connection
        died near-instantly) makes even the first dial back off."""
        # a reconnect dial is transport recovery, not device-work retry:
        # the loop shape stays bespoke (swap-under-lock, renegotiate) but
        # the attempt budget and delay schedule come from the client's
        # RetryPolicy dial policy (env-tunable), and attempts still sleep
        # via the sanctioned resilience.sleep_for
        for attempt in range(self.max_reconnects):  # qldpc: ignore[R102]
            if self._closed:
                return False
            if attempt or fast_death:
                resilience.sleep_for(self._dial_policy.delay(attempt))
            try:
                sock = socket.create_connection(
                    (self.host, self.port), timeout=self.timeout)
            except OSError:
                continue
            try:
                # renegotiate the wire codec on the FRESH socket before
                # the reader pumps it (the server may have been replaced
                # by one speaking a different codec set)
                codec = self._negotiate(sock)
            except (OSError, ValueError):
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            # swap + pong drain under ONE _wlock hold (nested _plock,
            # same _wlock->_plock order ping uses): a ping sent on the
            # NEW connection can only run before the swap (old-socket
            # pong, correctly failed below) or after the drain (new
            # pong, correctly kept) — never be spuriously failed
            with self._wlock:
                old, self._sock = self._sock, sock
                self.wire_codec = codec
                with self._plock:
                    closed = self._closed
                    pongs, self._pongs = list(self._pongs), deque()
            try:
                old.close()
            except OSError:
                pass
            for pong in pongs:
                if not pong.done():
                    pong.set_exception(
                        ConnectionError("connection replaced"))
            if closed:
                # close() ran mid-dial: it shut down the PREVIOUS socket,
                # so the fresh one must not strand the reader (and leak a
                # live TCP connection) — tear it down and exit
                try:
                    sock.close()
                except OSError:
                    pass
                return False
            self.reconnects += 1
            telemetry.count("serve.client.reconnects")
            self._resubmit_unanswered()
            return True
        return False

    def _resubmit_unanswered(self) -> None:
        """Send every unanswered logical request again with a fresh wire
        id and its original idempotency key — the server's journal
        attaches duplicates to in-flight decodes and replays
        already-answered ones, so a resubmit is always safe."""
        with self._plock:
            reqs = self._logical_reqs()
            sends = []
            fails = []
            for req in reqs:
                if req.future.done():
                    continue
                if req.base is None:
                    # unanswered requests with no retained frame (raw
                    # stream ops) cannot ride the resubmit: fail them NOW
                    # so their caller retries the same seq itself instead
                    # of hanging until the client timeout — the server
                    # replays committed seqs, so the retry is exact-once
                    for r in list(req.rids):
                        self._reqs.pop(r, None)
                    fails.append(req)
                    continue
                rid = f"{self._prefix}-{next(self._ids)}"
                req.rids.add(rid)
                req.resubmits += 1
                req.last_tx = time.perf_counter()
                self._reqs[rid] = req
                sends.append((req, {**req.base, "id": rid}))
        err = ConnectionError("connection replaced")
        for req in fails:
            if not req.future.done():
                req.future.set_exception(err)
        for req, msg in sends:
            try:
                self._send(msg)
                telemetry.count("serve.client.resubmits")
            except ValueError as exc:
                # unencodable frame (over the cap): resending can never
                # fix it — fail THIS request, keep resubmitting the rest
                self._fail_request(req, exc)
            except OSError:
                return  # socket died again; the reader loop redials

    def _hedge_loop(self) -> None:
        """Resubmit requests unanswered past the hedge deadline (same
        idempotency key — the server dedupes, so a hedge can only help)."""
        interval = max(0.001, self.hedge_s / 2.0)
        while not self._stop.wait(interval):
            now = time.perf_counter()
            with self._plock:
                sends = []
                for req in self._logical_reqs():
                    if req.future.done() or req.base is None \
                            or req.hedges >= self.max_hedges \
                            or now - req.last_tx < self.hedge_s:
                        continue
                    rid = f"{self._prefix}-{next(self._ids)}"
                    req.rids.add(rid)
                    req.hedges += 1
                    req.last_tx = now
                    self._reqs[rid] = req
                    sends.append((req, {**req.base, "id": rid}))
            for req, msg in sends:
                try:
                    self._send(msg)
                    telemetry.count("serve.client.hedges")
                except ValueError as exc:
                    self._fail_request(req, exc)  # unencodable: see above
                except OSError:
                    break  # dead socket: the reader owns recovery

    # ------------------------------------------------------------------
    def submit(self, session: str, syndromes, *,
               tenant: str | None = None,
               trace: "tracing.TraceContext | None" = None) -> Future:
        """Send one decode request; returns its future.  ``trace``
        attaches an explicit trace context; ``traced=True`` clients mint
        one per request when none is given.

        A send that hits a dead socket is a PER-REQUEST transient error:
        without ``reconnect`` the returned future carries a
        ``ConnectionError`` (the client object stays usable); with it,
        the request stays registered and rides the reconnect resubmit."""
        arr = np.atleast_2d(np.asarray(syndromes))
        n = next(self._ids)
        rid = f"{self._prefix}-{n}"
        if trace is None and self.traced:
            trace = tracing.TraceContext()
        # syndromes stay an ndarray in the base message: the packed codec
        # encodes them directly and the JSON path .tolist()s at encode
        # time — resubmittable clients retain ~8 bytes/shot-bit less than
        # the old pre-serialized int lists did
        base = {"op": "decode", "session": str(session),
                "tenant": tenant or self.tenant,
                "syndromes": np.asarray(arr, np.uint8)}
        if self.idempotent:
            base[IDEM_FIELD] = f"{self._idem_prefix}-i{n}"
        if trace is not None:
            base[TRACE_FIELD] = trace.to_wire()
        # only clients that can ever RESEND (reconnect resubmit / hedging)
        # need the frame retained until the answer; a plain client holding
        # the tolist() payload per in-flight request would pay ~10x the
        # syndrome bytes across its whole pipeline window for nothing
        resubmittable = self.reconnect or self._hedger is not None
        req = _Inflight(base if resubmittable else None,
                        time.perf_counter())
        with self._plock:
            if self._closed:
                raise RuntimeError("client closed")
            if self._dead:
                # the reader already declared the transport gone (and
                # drained the request table): registering now would leave
                # this future unresolved forever — and a send into the
                # dead socket can "succeed" into the buffer, so the error
                # must come from here, not from sendall
                req.future.set_exception(ConnectionError(
                    "decode-service connection closed"))
                return req.future
            req.rids.add(rid)
            self._reqs[rid] = req
        try:
            self._send({**base, "id": rid})
        except ValueError as exc:
            # over the frame cap: no reconnect or resend can ever fix
            # this payload, and leaving it registered would leak it (and
            # crash the resubmit/hedge threads re-encoding it) — fail
            # THIS request, the client stays healthy
            self._fail_request(req, exc)
        except OSError as exc:
            if not self.reconnect:
                # surface on THIS request only — a broken pipe must not
                # poison the client object (regression-tested with a torn
                # raw socket)
                with self._plock:
                    self._reqs.pop(rid, None)
                if not req.future.done():
                    req.future.set_exception(ConnectionError(
                        f"decode submit hit a dead connection: {exc}"))
            # with reconnect: leave it registered — the reader notices
            # the dead socket and resubmits on the fresh connection
        return req.future

    def decode(self, session: str, syndromes, *,
               tenant: str | None = None,
               trace: "tracing.TraceContext | None" = None) -> ClientResult:
        return self.submit(session, syndromes, tenant=tenant,
                           trace=trace).result(timeout=self.timeout)

    # ------------------------------------------------------------------
    # streaming decode (ISSUE 16)
    # ------------------------------------------------------------------
    def _submit_raw(self, msg: dict) -> Future:
        """Send one raw (stream) op; the future resolves with the raw
        response dict.  Never retained for resubmit or hedging — a raw
        request that loses its transport fails with ``ConnectionError``
        and its CALLER retries (the server's per-seq replay cache makes
        that exactly-once)."""
        rid = f"{self._prefix}-{next(self._ids)}"
        req = _Inflight(None, time.perf_counter(), raw=True)
        with self._plock:
            if self._closed:
                raise RuntimeError("client closed")
            if self._dead:
                req.future.set_exception(ConnectionError(
                    "decode-service connection closed"))
                return req.future
            req.rids.add(rid)
            self._reqs[rid] = req
        try:
            self._send({**msg, "id": rid})
        except ValueError as exc:
            self._fail_request(req, exc)
        except OSError as exc:
            # even with reconnect enabled a raw request does NOT ride the
            # resubmit (base is None): fail it here so the caller's retry
            # loop owns the resend
            self._fail_request(req, ConnectionError(
                f"stream op hit a dead connection: {exc}"))
        return req.future

    def _stream_rpc(self, msg: dict, *, retries: int = 8) -> dict:
        """Raw op + retry-on-transport-death loop.  Safe for every stream
        op: ``stream_open`` before any reply is idempotent-by-reopen-cost
        only at the caller's discretion (retried opens may mint an orphan
        stream server-side; harmless — shed/shutdown reaps it), and
        chunk/commit retries are deduplicated by the server's seq
        ledger."""
        last: Exception | None = None
        for attempt in range(max(1, int(retries))):  # qldpc: ignore[R102]
            if attempt:
                resilience.sleep_for(self._dial_policy.delay(attempt))
            try:
                return self._submit_raw(msg).result(timeout=self.timeout)
            except ConnectionError as exc:
                last = exc
                continue
        raise ConnectionError(
            f"stream op failed after {retries} attempts: {last}")

    def stream_open(self, profile: str, *, lanes: int = 1,
                    tenant: str | None = None, retries: int = 8) -> dict:
        """Open an overlap-commit stream on ``profile`` (a registered
        stream profile, or a bare session name for a frame-mode stream).
        Returns the server's open ack (``stream`` id, ``width``,
        ``cycles_per_window``); raises on a structured error."""
        res = self._stream_rpc({"op": "stream_open", "profile": str(profile),
                                "lanes": int(lanes),
                                "tenant": tenant or self.tenant},
                               retries=retries)
        if not res.get("ok"):
            raise RuntimeError(res.get("error", "stream_open failed"))
        return res

    def stream_chunk(self, stream: str, seq: int, chunk) -> Future:
        """Send one window's detector increment; the future resolves with
        the raw response dict (commit payload, replay, or structured
        error).  Most callers want ``stream_step``."""
        arr = np.atleast_2d(np.asarray(chunk, np.uint8))
        return self._submit_raw({"op": "stream_chunk", "stream": str(stream),
                                 "seq": int(seq), "chunk": arr})

    def stream_step(self, stream: str, seq: int, chunk, *,
                    retries: int = 8) -> dict:
        """One committed window: send ``(stream, seq, chunk)`` and block
        for the commit payload.  A transport death or a transient "busy"
        retries the SAME seq — the server's commit-before-respond ledger
        either decodes it (never committed) or replays the cached commit
        (response lost on the wire), so the window lands exactly once.
        Terminal structured errors (shed, unknown stream, gap/stale)
        return the raw dict for the caller's resume logic."""
        arr = np.atleast_2d(np.asarray(chunk, np.uint8))
        msg = {"op": "stream_chunk", "stream": str(stream),
               "seq": int(seq), "chunk": arr}
        last: Exception | None = None
        for attempt in range(max(1, int(retries))):  # qldpc: ignore[R102]
            if attempt:
                resilience.sleep_for(self._dial_policy.delay(attempt))
            try:
                res = self._submit_raw(msg).result(timeout=self.timeout)
            except ConnectionError as exc:
                last = exc
                continue
            if res.get("stream_error") == "busy":
                # the previous transmission of this seq is still decoding
                # server-side (our response died on the wire): wait for
                # its commit, then the retry replays from cache
                last = RuntimeError(res.get("error", "stream busy"))
                continue
            return res
        raise ConnectionError(
            f"stream step seq={seq} failed after {retries} attempts: {last}")

    def stream_commit(self, stream: str, *, close: bool = False,
                      retries: int = 8) -> dict:
        """Commit-watermark query (the resume handshake after a kill) or,
        with ``close=True``, retire the stream."""
        msg = {"op": "stream_commit", "stream": str(stream)}
        if close:
            msg["close"] = True
        return self._stream_rpc(msg, retries=retries)

    def ping(self) -> dict:
        fut: Future = Future()
        # register + send atomically under the WRITE lock: pongs match
        # waiters FIFO, so the waiter-queue order must equal the on-wire
        # send order (two threads racing between the two steps would
        # receive each other's pong).  Lock order is _wlock -> _plock;
        # no other path nests them, so no inversion.
        with self._wlock:
            with self._plock:
                if self._closed:
                    raise RuntimeError("client closed")
                if self._dead:
                    # no reader is alive to match a pong: a send could
                    # still "succeed" into the dead socket's buffer and
                    # the caller would block the full timeout
                    raise ConnectionError(
                        "decode-service connection closed")
                self._pongs.append(fut)
            frame = encode_frame({"op": "ping"})
            telemetry.count("serve.client.bytes_tx", len(frame))
            self._sock.sendall(frame)
        return fut.result(timeout=self.timeout)

    def close(self) -> None:
        with self._plock:
            self._closed = True
        self._stop.set()
        # the CURRENT socket, atomically with any in-flight reconnect
        # swap (the swap's own post-swap _closed check covers the other
        # interleaving: a socket swapped in after this closes itself)
        with self._wlock:
            sock = self._sock
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        sock.close()
        self._reader.join(timeout=10.0)
        if self._hedger is not None:
            self._hedger.join(timeout=10.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
