"""Blocking decode-service client with pipelined submits.

A thin stdlib-socket counterpart to serve/server.py's protocol: ``submit``
sends a decode frame and returns a future immediately (responses stream
back in completion order and are matched by id on a background reader
thread), so a load generator keeps a window of requests in flight without
one connection per request.  ``decode`` is the submit+wait convenience.

Latency is measured CLIENT-side (submit to response-parsed), which is the
number a tail-latency SLO is actually about — it includes the wire, the
queue wait, the batch fill and the dispatch.

Tracing (ISSUE 11): construct with ``traced=True`` (or pass ``trace=`` per
submit) and every request mints a ``utils.tracing.TraceContext`` that
rides the optional wire field — the server records the full stage-span
tree under it and echoes the trace id back on ``ClientResult.trace_id``,
the key for the JSONL stream and ``/tracez``.  Untraced clients send
byte-identical frames to pre-tracing builds.
"""
from __future__ import annotations

import dataclasses
import itertools
import json
import socket
import threading
import time
import uuid
from collections import deque
from concurrent.futures import Future

import numpy as np

from ..utils import tracing
from .wire import HEADER, MAX_FRAME_BYTES, TRACE_FIELD, encode_frame

__all__ = ["ClientResult", "DecodeClient"]


@dataclasses.dataclass
class ClientResult:
    corrections: np.ndarray          # (k, n) uint8
    converged: list | None
    latency_s: float                 # client-side: submit -> response parsed
    server_latency_ms: float | None  # scheduler-side, from the response
    request_id: str
    trace_id: str | None = None      # echoed by the server when traced


class DecodeClient:
    def __init__(self, host: str, port: int, *, tenant: str = "default",
                 timeout: float = 60.0, traced: bool = False):
        self.tenant = str(tenant)
        self.traced = bool(traced)
        self.timeout = float(timeout)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._wlock = threading.Lock()
        self._plock = threading.Lock()
        self._pending: dict[str, tuple[Future, float]] = {}
        # ping waiters queue FIFO (pongs come back in order): concurrent
        # pings from threads sharing one client each get their own future
        self._pongs: deque[Future] = deque()
        self._closed = False
        self._ids = itertools.count()
        self._prefix = uuid.uuid4().hex[:8]
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name="qldpc-serve-client")
        self._reader.start()

    # ------------------------------------------------------------------
    def _send(self, obj) -> None:
        frame = encode_frame(obj)
        with self._wlock:
            self._sock.sendall(frame)

    def _recv_exact(self, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            try:
                chunk = self._sock.recv(n - len(buf))
            except socket.timeout:
                # idle is NOT disconnect: a low-traffic client must keep
                # its reader alive past the socket timeout (close() breaks
                # the loop via shutdown -> OSError below)
                if self._closed:
                    return None
                continue
            except (OSError, ValueError):
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    def _read_loop(self) -> None:
        while True:
            head = self._recv_exact(HEADER.size)
            if head is None:
                break
            (length,) = HEADER.unpack(head)
            if length > MAX_FRAME_BYTES:
                break  # protocol corruption — fail pending via loop exit
            body = self._recv_exact(length)
            if body is None:
                break
            try:
                msg = json.loads(body.decode("utf-8"))
            except json.JSONDecodeError:
                continue
            if msg.get("pong"):
                with self._plock:
                    pong = self._pongs.popleft() if self._pongs else None
                if pong is not None:
                    pong.set_result(msg)
                continue
            rid = msg.get("id")
            with self._plock:
                entry = self._pending.pop(rid, None)
            if entry is None:
                continue
            fut, t0 = entry
            if msg.get("ok"):
                fut.set_result(ClientResult(
                    corrections=np.asarray(msg["corrections"], np.uint8),
                    converged=msg.get("converged"),
                    latency_s=time.perf_counter() - t0,
                    server_latency_ms=msg.get("latency_ms"),
                    request_id=str(rid),
                    trace_id=msg.get("trace_id")))
            else:
                fut.set_exception(
                    RuntimeError(msg.get("error", "decode failed")))
        # socket gone: fail whatever is still outstanding
        with self._plock:
            pending, self._pending = self._pending, {}
            pongs, self._pongs = list(self._pongs), deque()
        err = ConnectionError("decode-service connection closed")
        for fut, _ in pending.values():
            if not fut.done():
                fut.set_exception(err)
        for pong in pongs:
            if not pong.done():
                pong.set_exception(err)

    # ------------------------------------------------------------------
    def submit(self, session: str, syndromes, *,
               tenant: str | None = None,
               trace: "tracing.TraceContext | None" = None) -> Future:
        """Send one decode request; returns its future.  ``trace``
        attaches an explicit trace context; ``traced=True`` clients mint
        one per request when none is given."""
        arr = np.atleast_2d(np.asarray(syndromes))
        rid = f"{self._prefix}-{next(self._ids)}"
        if trace is None and self.traced:
            trace = tracing.TraceContext()
        fut: Future = Future()
        with self._plock:
            if self._closed:
                raise RuntimeError("client closed")
            self._pending[rid] = (fut, time.perf_counter())
        msg = {"op": "decode", "id": rid, "session": str(session),
               "tenant": tenant or self.tenant,
               "syndromes": arr.tolist()}
        if trace is not None:
            msg[TRACE_FIELD] = trace.to_wire()
        try:
            self._send(msg)
        except OSError:
            with self._plock:
                self._pending.pop(rid, None)
            raise
        return fut

    def decode(self, session: str, syndromes, *,
               tenant: str | None = None,
               trace: "tracing.TraceContext | None" = None) -> ClientResult:
        return self.submit(session, syndromes, tenant=tenant,
                           trace=trace).result(timeout=self.timeout)

    def ping(self) -> dict:
        fut: Future = Future()
        # register + send atomically under the WRITE lock: pongs match
        # waiters FIFO, so the waiter-queue order must equal the on-wire
        # send order (two threads racing between the two steps would
        # receive each other's pong).  Lock order is _wlock -> _plock;
        # no other path nests them, so no inversion.
        with self._wlock:
            with self._plock:
                if self._closed:
                    raise RuntimeError("client closed")
                self._pongs.append(fut)
            self._sock.sendall(encode_frame({"op": "ping"}))
        return fut.result(timeout=self.timeout)

    def close(self) -> None:
        with self._plock:
            self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()
        self._reader.join(timeout=10.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
