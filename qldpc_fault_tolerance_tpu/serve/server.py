"""Asyncio front-end for the decode service: stdlib TCP, length-prefixed
frames (JSON v1 or packed-binary v2), streamed per-request responses,
graceful drain.

Wire protocol (no dependencies beyond the stdlib; serve/wire.py owns the
codec):

    frame    := uint32 big-endian payload length | payload
    payload  := one UTF-8 JSON object (v1) | packed binary (v2, ISSUE 15)

Requests (client -> server; v2 ships the same fields with the syndromes as
a packed gf2_packed body instead of a JSON matrix):
    {"op": "decode", "id": <str>, "session": <name>, "tenant": <str>,
     "syndromes": [[0,1,...], ...],
     "trace": {"trace_id": ..., "span_id": ...}}   # OPTIONAL (ISSUE 11)
    {"op": "ping"}
    {"op": "hello", "codecs": [2, 1]}              # codec negotiation

Responses (server -> client; decode responses stream back in COMPLETION
order, matched by "id" — a slow megabatch never head-of-line-blocks a fast
one — and each response is encoded in the codec its request arrived in):
    {"id": ..., "ok": true, "corrections": [[...], ...],
     "converged": [true, ...] | null, "latency_ms": <float>,
     "trace_id": "..."}                            # echoed when traced
    {"id": ..., "ok": false, "error": "...", "shed": true?}
    {"ok": true, "pong": true, "sessions": [...], "draining": false}
    {"ok": true, "hello": true, "codec": 2, "codecs": [1, 2], ...}

A traced request (optional "trace" field, utils.tracing.TraceContext wire
shape) gets a ``serve.request`` root span covering submit -> response
serialized, parented to the client's span; the batcher records the stage
spans (queue_wait / batch_assemble / pad / device_decode / slice) under
it and the server adds the ``respond`` span.  A tenant shed by the SLO
admission signal (serve.ops) is answered with ``"shed": true`` — refused
loudly and cheaply, never queued and timed out.

Codec handling: JSON keeps the protocol inspectable; v2 (negotiated via
"hello" at connect, self-describing per frame through the magic) ships the
bitplanes in the gf2_packed device layout — mixed v1/v2 clients coexist on
one server.  A malformed BINARY payload is answered with a structured
error and the connection keeps serving (the outer frame boundary is
intact); malformed JSON keeps its pre-v2 semantics (answer, then close —
v1 framing errors are indistinguishable from stream corruption).
``serve.bytes_rx`` / ``serve.bytes_tx`` count every framed byte both ways
and the ``wire.codec_version`` gauge records the last negotiated codec.

``shutdown(drain=True)`` is the graceful path: stop accepting connections,
reject NEW decode ops with an error response, drain the batcher (every
accepted request completes and its response is written) and only then close
— no accepted request is ever dropped (tests/test_serve.py pins this).
"""
from __future__ import annotations

import asyncio
import json
import threading
import time
import uuid

import numpy as np

from ..utils import faultinject, telemetry, tracing
from .ops import AdmissionError, spawn_server_loop
from .scheduler import ContinuousBatcher
from .session import StreamProfile, StreamProtocolError, StreamSession
from .wire import (
    HEADER,
    IDEM_FIELD,
    MAX_FRAME_BYTES,
    ROUTE_FIELD,
    TRACE_FIELD,
    WIRE_CODEC_JSON,
    WIRE_CODEC_PACKED,
    WIRE_CODECS,
    WireCodecError,
    decode_payload,
    encode_frame,
    encode_response_frame,
)

__all__ = ["DecodeServer", "ServerHandle", "start_server_thread",
           "MAX_FRAME_BYTES", "encode_frame"]


# idempotency keys are wire-controlled strings that key the scheduler's
# journal — bound them like trace ids; an oversize key is treated as
# absent (counted), never an error that kills the request
_MAX_IDEM_CHARS = 128


def _wire_idem(msg) -> str | None:
    idem = msg.get(IDEM_FIELD)
    if not isinstance(idem, str) or not idem:
        return None
    if len(idem) > _MAX_IDEM_CHARS:
        telemetry.count("serve.idem_oversize")
        return None
    return idem


async def read_frame(reader: asyncio.StreamReader):
    """One length-prefixed payload's RAW bytes, or None on EOF /
    disconnect — including a client dropping MID-frame (after the header,
    before the full body), which must take the clean-disconnect path, not
    kill the connection task with an unretrieved exception.  Decoding
    (JSON v1 / packed v2) is the caller's ``wire.decode_payload``."""
    try:
        head = await reader.readexactly(HEADER.size)
        (length,) = HEADER.unpack(head)
        if length > MAX_FRAME_BYTES:
            raise ValueError(f"frame of {length} bytes exceeds the "
                             f"{MAX_FRAME_BYTES}-byte cap")
        body = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    return body


class DecodeServer:
    """The asyncio service: accepts connections, feeds decode ops to the
    ContinuousBatcher, streams responses back per request."""

    def __init__(self, batcher: ContinuousBatcher, host: str = "127.0.0.1",
                 port: int = 0, stream_profiles: dict | None = None):
        self.batcher = batcher
        self.host = host
        self.port = int(port)
        self._server: asyncio.AbstractServer | None = None
        self._tasks: set[asyncio.Task] = set()
        self._conns: set[asyncio.Task] = set()
        self._draining = False
        # streaming decode (ISSUE 16): named open recipes + the live
        # per-stream overlap-commit sessions.  A registered session name
        # doubles as an implicit frame-mode profile, so phenom-style
        # streams need no registration.
        self.stream_profiles: dict[str, StreamProfile] = dict(
            stream_profiles or {})
        self._streams: dict[str, StreamSession] = {}
        self._stream_counter = 0
        # stream ids carry a per-server random prefix (ISSUE 18): a fleet
        # re-homes streams ACROSS hosts by id, and two hosts both minting
        # "st-0001" would collide in the successor's ledger on handoff
        self._stream_prefix = uuid.uuid4().hex[:6]
        # routing-epoch fence (ISSUE 18): family -> (epoch, own).  Set by
        # the fleet router's ``family_adopt`` broadcasts; a routed frame
        # whose (family, epoch) this host does not currently own is
        # refused with ``route_stale`` so a partitioned router's stale
        # placement can never cause a double decode on the old owner.
        # Direct (un-routed) frames bypass the fence entirely — single-
        # host deployments never see it.
        self._family_epochs: dict[str, tuple[int, bool]] = {}

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    # ------------------------------------------------------------------
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conns.add(task)
            task.add_done_callback(self._conns.discard)
        wlock = asyncio.Lock()
        try:
            while True:
                try:
                    payload = await read_frame(reader)
                except ValueError as exc:
                    await self._write(writer, wlock,
                                      {"ok": False,
                                       "error": f"bad frame: {exc}"})
                    break
                if payload is None:
                    break
                telemetry.count("serve.bytes_rx",
                                len(payload) + HEADER.size)
                # network chaos (ISSUE 14): under a fault plan this frame
                # may be answered with a torn frame and/or the connection
                # hard-dropped — the client's reconnect + resubmit path
                # (deduped by the scheduler journal) must recover
                if await self._consume_conn_fault(
                        lambda on: faultinject.site(
                            "serve_conn_rx",
                            actions={"conn_drop": on, "torn_frame": on,
                                     "stall": on}),
                        writer, wlock):
                    break
                try:
                    msg = decode_payload(payload)
                except WireCodecError as exc:
                    # malformed v2 payload: the OUTER frame boundary is
                    # intact (the length prefix framed it), so only THIS
                    # request is lost — answer a structured error and
                    # keep serving everything pipelined on the connection
                    telemetry.count("serve.wire_errors")
                    await self._write(writer, wlock, {
                        "id": exc.request_id, "ok": False,
                        "error": f"bad frame: {exc}"})
                    continue
                except (json.JSONDecodeError, UnicodeDecodeError) as exc:
                    await self._write(writer, wlock,
                                      {"ok": False,
                                       "error": f"bad frame: {exc}"})
                    break
                if not isinstance(msg, dict):
                    # valid JSON but not an object: a structured reply,
                    # not a dead connection for everything pipelined on it
                    await self._write(writer, wlock, {
                        "ok": False,
                        "error": f"frame must be a JSON object, got "
                                 f"{type(msg).__name__}"})
                    continue
                op = msg.get("op")
                route = msg.pop(ROUTE_FIELD, None)
                if route is not None and not self._route_ok(route):
                    # the epoch fence: this host does not (or no longer)
                    # own(s) the frame's family at the router's epoch —
                    # refuse loudly so the router re-resolves placement
                    # and re-forwards; dispatching here could double-
                    # decode against the family's real owner
                    telemetry.count("serve.route_stale")
                    cur = self._family_epochs.get(str(route.get("family")))
                    await self._write(writer, wlock, {
                        "id": msg.get("id"), "ok": False,
                        "route_stale": True,
                        "family": route.get("family"),
                        "epoch": 0 if cur is None else int(cur[0]),
                        "error": "routed frame fenced: host does not own "
                                 "this family at that epoch"})
                    continue
                if op == "ping":
                    await self._write(writer, wlock, {
                        "ok": True, "pong": True,
                        "sessions": self.batcher.sessions.names(),
                        "draining": self._draining})
                elif op == "decode":
                    await self._handle_decode(msg, writer, wlock)
                elif op == "hello":
                    await self._write(writer, wlock, self._hello(msg))
                elif op == "stream_open":
                    await self._write(writer, wlock, self._stream_open(msg))
                elif op == "stream_chunk":
                    if await self._handle_stream_chunk(msg, writer, wlock):
                        break  # chaos killed the connection mid-window
                elif op == "stream_commit":
                    await self._write(writer, wlock,
                                      self._stream_commit(msg))
                elif op == "family_adopt":
                    await self._write(writer, wlock,
                                      self._family_adopt(msg))
                elif op == "journal_export":
                    await self._write(writer, wlock,
                                      self._journal_export(msg))
                elif op == "journal_import":
                    await self._write(writer, wlock,
                                      self._journal_import(msg))
                else:
                    await self._write(writer, wlock, {
                        "id": msg.get("id"), "ok": False,
                        "error": f"unknown op {op!r}"})
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _consume_conn_fault(self, consult, writer, wlock) -> bool:
        """Consult one wire chaos site and enact the result: ``consult``
        performs the literal ``faultinject.site`` call (the literal stays
        at the call site — R008 pins one plant per site name) with a
        shared on-hit callback for the kinds that site enacts.  A
        stall-kind fault sleeps ASYNC so it stalls only this connection,
        never the event loop; drop kinds (and any raise-kind fault at the
        site) kill the connection.  Returns True when the connection is
        dead and the caller must stop using it."""
        hit = []
        try:
            consult(hit.append)
        except Exception:  # noqa: BLE001 — raise kinds drop the conn too
            hit.append(None)
        if not hit:
            return False
        fault = hit[0]
        if fault is not None and fault.kind == "stall":
            await asyncio.sleep(fault.stall_s)
            return False
        await self._enact_conn_fault(writer, wlock, fault)
        return True

    @staticmethod
    async def _enact_conn_fault(writer, wlock, fault) -> None:
        """Enact one network chaos fault: ``torn_frame`` writes a length
        header promising more bytes than follow (the torn wire a dying
        peer leaves) and then drops; ``conn_drop`` (and any raise-kind
        fault at the site, passed as None) hard-aborts the transport
        without flushing.  After this the connection is dead and the
        caller must stop serving it."""
        if fault is not None and fault.kind == "torn_frame":
            try:
                async with wlock:
                    # header claims a full frame; only a prefix follows
                    writer.write(HEADER.pack(1 << 16) + b'{"torn":')
                    await writer.drain()
            except (ConnectionError, RuntimeError):
                pass
        telemetry.count("serve.chaos.conn_drops")
        try:
            writer.transport.abort()
        except Exception:  # noqa: BLE001 — already dead is fine
            pass

    def _hello(self, msg) -> dict:
        """Codec negotiation (ISSUE 15): pick the highest wire codec both
        ends speak.  The reply tells the client what to SEND; responses
        always mirror each request's arrival codec, so the negotiation
        never needs per-connection state server-side."""
        offered = msg.get("codecs")
        if not isinstance(offered, (list, tuple)):
            offered = [WIRE_CODEC_JSON]
        usable = [int(c) for c in offered
                  if isinstance(c, (int, float)) and int(c) in WIRE_CODECS]
        codec = max(usable, default=WIRE_CODEC_JSON)
        telemetry.count(f"serve.codec.v{codec}_hellos")
        telemetry.set_gauge("wire.codec_version", codec)
        return {"ok": True, "hello": True, "codec": codec,
                "codecs": list(WIRE_CODECS),
                "streams": True,
                "sessions": self.batcher.sessions.names(),
                "draining": self._draining}

    # ------------------------------------------------------------------
    # fleet handoff plane (ISSUE 18): epoch fence + journal replication
    # ------------------------------------------------------------------
    def _route_ok(self, route) -> bool:
        """May a routed frame dispatch here?  Only when this host has been
        told (via ``family_adopt``) that it OWNS the frame's family, at an
        epoch no newer than the frame's — an un-adopted family or a frame
        carrying an older epoch than our fence means the router's
        placement view and ours disagree, and the router must re-resolve."""
        if not isinstance(route, dict):
            return False
        cur = self._family_epochs.get(str(route.get("family")))
        if cur is None or not cur[1]:
            return False
        try:
            return int(route.get("epoch", -1)) >= int(cur[0])
        except (TypeError, ValueError):
            return False

    def _family_adopt(self, msg) -> dict:
        """The router's placement assertion: ``own=True`` makes this host
        the family's dispatching owner at ``epoch``; ``own=False`` fences
        it off (the old owner after a handoff, or every non-owner on a
        placement broadcast).  Monotone in epoch — an older assertion
        (a partitioned router's late broadcast) never rolls the fence
        back.  Idempotent, so the router re-asserts freely."""
        rid = msg.get("id")
        family = str(msg.get("family", ""))
        if not family:
            return {"id": rid, "ok": False, "error": "family_adopt misses "
                                                     "its family"}
        try:
            epoch = int(msg.get("epoch", 0))
        except (TypeError, ValueError):
            return {"id": rid, "ok": False,
                    "error": f"bad epoch {msg.get('epoch')!r}"}
        own = bool(msg.get("own", True))
        cur = self._family_epochs.get(family)
        if cur is not None and epoch < cur[0]:
            return {"id": rid, "ok": False, "stale_epoch": True,
                    "family": family, "epoch": int(cur[0]),
                    "error": f"adopt epoch {epoch} is behind fence "
                             f"{cur[0]}"}
        missing = [s for s in (msg.get("sessions") or ())
                   if s not in self.batcher.sessions]
        if own and missing:
            return {"id": rid, "ok": False, "family": family,
                    "missing_sessions": missing,
                    "error": f"cannot adopt {family}: sessions {missing} "
                             "not resident on this host"}
        changed = cur != (epoch, own)
        self._family_epochs[family] = (epoch, own)
        if changed:
            # the router re-asserts placement periodically (idempotent
            # broadcasts) — only a real transition is worth an event
            telemetry.count("serve.family_adopts")
            telemetry.event("scale_event", action="family_adopt",
                            target=family, to_value=epoch,
                            reason=("own" if own else "fence"))
        return {"id": rid, "ok": True, "family": family, "epoch": epoch,
                "own": own}

    def _journal_export(self, msg) -> dict:
        """One replication pull: the scheduler's answered-LRU delta after
        the caller's watermark, plus every open stream's committed state
        (small: a carry plane + the cached replay response per stream).
        The fleet router feeds these to the family's successor so a
        handoff replays instead of re-decoding."""
        rid = msg.get("id")
        try:
            since = int(msg.get("since", 0))
        except (TypeError, ValueError):
            return {"id": rid, "ok": False,
                    "error": f"bad since {msg.get('since')!r}"}
        snap = self.batcher.export_journal(since=since)
        snap["streams"] = [s.export_state()
                           for s in list(self._streams.values())]
        # warm-program manifest (ISSUE 20): which (bucket, sharded)
        # programs each resident session is serving warm.  The router
        # forwards it to the family's ring successor, which pre-LOADS the
        # same programs from the persistent cache — adoption then answers
        # its first frame without a compile stall.
        programs = {}
        for name in self.batcher.sessions.names():
            try:
                sess = self.batcher.sessions.get(name)
                keys = getattr(sess, "warm_keys", None)
                if callable(keys):
                    warm = keys()
                    if warm:
                        programs[name] = warm
            except Exception:  # noqa: BLE001 — eviction race: skip
                continue
        snap["programs"] = programs
        return {"id": rid, "ok": True, **snap}

    def _journal_import(self, msg) -> dict:
        """One replication push: merge a peer host's ``journal_export``
        delta.  Answered entries join the local answered-LRU (idempotent
        by key); stream states rebuild or advance local ``StreamSession``
        ledgers under their ORIGINAL ids, so after adoption the client's
        same-seq retries replay or resume exactly-once."""
        rid = msg.get("id")
        snap = msg.get("snapshot")
        if not isinstance(snap, dict):
            return {"id": rid, "ok": False,
                    "error": "journal_import misses its snapshot"}
        imported = self.batcher.import_journal(snap)
        streams = 0
        for state in snap.get("streams", ()):
            sid = state.get("stream")
            if not sid:
                continue
            stream = self._streams.get(sid)
            if stream is None:
                stream = self._rebuild_stream(state)
                if stream is None:
                    telemetry.count("serve.stream_import_failures")
                    continue
                self._streams[sid] = stream
                telemetry.set_gauge("stream.open_streams",
                                    len(self._streams))
            if stream.import_state(state):
                streams += 1
        # warm-start pre-load (ISSUE 20): LOAD the pushed manifest's
        # programs from the persistent cache — strictly load-only
        # (``adopt_program`` never compiles; a miss is a no-op), because
        # this runs on the control plane of a host that is still serving
        # its own families and a compile here would stall live traffic.
        loaded = 0
        for name, keys in (snap.get("programs") or {}).items():
            try:
                sess = self.batcher.sessions.get(str(name))
            except KeyError:
                continue
            adopt = getattr(sess, "adopt_program", None)
            if not callable(adopt):
                continue
            for entry in keys or ():
                try:
                    bucket, sharded = entry
                    if adopt(int(bucket), bool(sharded)):
                        loaded += 1
                        telemetry.count("serve.progcache_warm_loaded")
                    else:
                        telemetry.count("serve.progcache_warm_skipped")
                except Exception:  # noqa: BLE001 — warm-start best effort
                    telemetry.count("serve.progcache_warm_skipped")
        return {"id": rid, "ok": True, "imported": int(imported),
                "streams": int(streams), "programs_loaded": int(loaded),
                "watermark": int(snap.get("watermark", 0))}

    def _rebuild_stream(self, state) -> "StreamSession | None":
        """Reconstruct a replicated stream's ledger from its exported
        state: the profile (or bare session, frame mode) must be resident
        here — the router only pairs hosts serving the same session set."""
        name = str(state.get("profile") or "")
        profile = self.stream_profiles.get(name)
        if profile is None:
            if name not in self.batcher.sessions:
                return None
            profile = StreamProfile(session=name)
        try:
            session = self.batcher.sessions.get(profile.session)
            stream = StreamSession(
                str(state["stream"]), session,
                lanes=int(state.get("lanes", 1)),
                space_cor=profile.space_cor, log_mat=profile.log_mat,
                cycles_per_window=profile.cycles_per_window,
                tenant=str(state.get("tenant", "default")))
        except (KeyError, ValueError, TypeError):
            return None
        stream.profile_name = name
        return stream

    # ------------------------------------------------------------------
    # streaming decode (ISSUE 16)
    # ------------------------------------------------------------------
    def _stream_open(self, msg) -> dict:
        """Open one stream: mint an id, build the per-stream overlap-
        commit ledger over the profile's DecodeSession.  A registered
        session name with no profile opens a frame-mode stream on it."""
        rid = msg.get("id")
        if self._draining:
            return {"id": rid, "ok": False, "error": "server is draining"}
        name = str(msg.get("profile") or msg.get("session") or "")
        profile = self.stream_profiles.get(name)
        if profile is None:
            try:
                self.batcher.sessions.get(name)
            except KeyError:
                return {"id": rid, "ok": False,
                        "error": f"unknown stream profile or session "
                                 f"{name!r}"}
            profile = StreamProfile(session=name)
        try:
            session = self.batcher.sessions.get(profile.session)
        except KeyError:
            return {"id": rid, "ok": False,
                    "error": f"stream profile {name!r} names unknown "
                             f"session {profile.session!r}"}
        tenant = str(msg.get("tenant", "default"))
        try:
            lanes = int(msg.get("lanes", 1))
        except (TypeError, ValueError):
            return {"id": rid, "ok": False,
                    "error": f"lanes must be an int, got "
                             f"{msg.get('lanes')!r}"}
        self._stream_counter += 1
        sid = f"st-{self._stream_prefix}-{self._stream_counter:04d}"
        try:
            stream = StreamSession(
                sid, session, lanes=lanes, space_cor=profile.space_cor,
                log_mat=profile.log_mat,
                cycles_per_window=profile.cycles_per_window, tenant=tenant)
        except ValueError as exc:
            return {"id": rid, "ok": False, "error": str(exc)}
        # the opening profile name travels with the stream's exported
        # state so a successor host can rebuild the ledger on handoff
        stream.profile_name = name
        self._streams[sid] = stream
        telemetry.count("stream.opens")
        telemetry.set_gauge("stream.open_streams", len(self._streams))
        telemetry.event("stream_open", stream=sid, session=profile.session,
                        tenant=tenant, lanes=stream.lanes,
                        width=stream.width,
                        cycles_per_window=stream.cycles_per_window)
        return {"id": rid, "ok": True, "stream": sid, "committed": 0,
                "lanes": stream.lanes, "width": stream.width,
                "cycles_per_window": stream.cycles_per_window}

    def _stream_commit(self, msg) -> dict:
        """Watermark query / close: the resume handshake.  After a kill
        mid-window the client asks where to continue; ``close`` retires
        the stream."""
        rid = msg.get("id")
        sid = msg.get("stream")
        stream = self._streams.get(sid)
        if stream is None:
            return {"id": rid, "ok": False, "stream": sid,
                    "stream_unknown": True,
                    "error": f"unknown stream {sid!r} (shed, closed, or "
                             "never opened)"}
        snap = stream.snapshot()
        if msg.get("close"):
            self._streams.pop(sid, None)
            info = stream.close()
            telemetry.set_gauge("stream.open_streams", len(self._streams))
            telemetry.event("stream_close", stream=str(sid),
                            committed=info["committed"],
                            committed_cycles=info["committed_cycles"],
                            reason="client")
            snap["closed"] = True
        return {"id": rid, "ok": True, **snap}

    async def _handle_stream_chunk(self, msg, writer, wlock) -> bool:
        """One window's detector increment.  Returns True when chaos
        killed the connection (the caller stops serving it).

        Commit protocol: the chunk decodes through the batcher (journaled
        ``stream:<id>:<seq>`` idempotency key, co-family fusion for free),
        then the StreamSession folds the corrections into the carry and
        advances the watermark atomically — replays of a committed seq get
        the cached response without re-decoding, so a kill anywhere in
        this path loses at most uncommitted work, never doubles a commit."""
        rid = msg.get("id")
        codec = int(msg.get("_codec", WIRE_CODEC_JSON))
        sid = msg.get("stream")
        stream = self._streams.get(sid)
        if stream is None:
            await self._write(writer, wlock, {
                "id": rid, "ok": False, "stream": sid,
                "stream_unknown": True,
                "error": f"unknown stream {sid!r} (shed, closed, or "
                         "never opened)"})
            return False
        # stream chaos: the step dies mid-window — after the chunk was
        # read, before decode/commit.  Nothing was committed, so the
        # client's resume path (stream_commit watermark query + resend)
        # must land the window exactly once.
        if await self._consume_conn_fault(
                lambda on: faultinject.site(
                    "serve_stream_step",
                    actions={"stream_kill": on, "conn_drop": on,
                             "stall": on}),
                writer, wlock):
            return True
        seq = msg.get("seq")
        chunk = msg.get("chunk")
        if chunk is None:
            await self._write(writer, wlock, {
                "id": rid, "ok": False, "stream": stream.stream_id,
                "error": "stream chunk misses its chunk plane"})
            return False
        try:
            action, staged = stream.prepare(seq, chunk)
        except StreamProtocolError as exc:
            telemetry.count("stream.protocol_errors")
            await self._write(writer, wlock, {
                "id": rid, "ok": False, "stream": stream.stream_id,
                "stream_error": exc.code, "committed": stream.committed,
                "error": str(exc)})
            return False
        if action == "replay":
            payload = dict(staged, id=rid, replayed=True)
            await self._write_stream_response(writer, wlock, payload, codec)
            return False
        try:
            fut = self.batcher.submit(
                stream.session.name, staged, tenant=stream.tenant,
                request_id=None if rid is None else str(rid),
                idem=f"stream:{stream.stream_id}:{int(seq)}")
        except AdmissionError as exc:
            # the streaming SLO rung: burn-rate pressure sheds the WHOLE
            # stream, not one chunk — its state is dropped, the client is
            # told loudly, and subsequent chunks answer "unknown stream"
            # (reopen when the burn subsides)
            stream.abort(int(seq))
            self._streams.pop(stream.stream_id, None)
            stream.close()
            telemetry.count("stream.shed")
            telemetry.set_gauge("stream.open_streams", len(self._streams))
            telemetry.event("stream_shed", stream=stream.stream_id,
                            tenant=exc.tenant, committed=stream.committed,
                            burn_rate=float(exc.burn_rate),
                            signal=str(exc.signal))
            await self._write(writer, wlock, {
                "id": rid, "ok": False, "stream": stream.stream_id,
                "shed": True, "stream_shed": True,
                "committed": stream.committed,
                "error": f"{type(exc).__name__}: {exc}"})
            return False
        except Exception as exc:  # noqa: BLE001 — answered, not dropped
            stream.abort(int(seq))
            await self._write(writer, wlock, {
                "id": rid, "ok": False, "stream": stream.stream_id,
                "committed": stream.committed,
                "error": f"{type(exc).__name__}: {exc}"})
            return False
        task = asyncio.ensure_future(self._stream_respond(
            rid, stream, int(seq), fut, writer, wlock, codec))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return False

    async def _stream_respond(self, rid, stream, seq, fut, writer, wlock,
                              codec) -> None:
        try:
            res = await asyncio.wrap_future(fut)
        except Exception as exc:  # noqa: BLE001
            stream.abort(seq)
            try:
                await self._write(writer, wlock, {
                    "id": rid, "ok": False, "stream": stream.stream_id,
                    "committed": stream.committed,
                    "error": f"{type(exc).__name__}: {exc}"})
            except (ConnectionError, RuntimeError):
                pass
            return
        try:
            payload = stream.commit(seq, res.corrections,
                                    converged=res.converged)
        except StreamProtocolError as exc:
            # the stream was shed/closed while its decode was in flight
            try:
                await self._write(writer, wlock, {
                    "id": rid, "ok": False, "stream": stream.stream_id,
                    "stream_error": exc.code,
                    "committed": stream.committed, "error": str(exc)})
            except (ConnectionError, RuntimeError):
                pass
            return
        payload["id"] = rid
        payload["latency_ms"] = round(res.latency_s * 1e3, 3)
        try:
            await self._write_stream_response(writer, wlock, payload, codec)
        except (ConnectionError, RuntimeError):
            # the commit stands; a reconnecting client replays this seq
            # and gets the cached response
            pass

    async def _write_stream_response(self, writer, wlock, payload,
                                     codec) -> None:
        if codec != WIRE_CODEC_PACKED:
            payload = dict(payload,
                           corrections=np.asarray(
                               payload["corrections"]).tolist())
        await self._write(writer, wlock, payload, codec=codec)

    async def _handle_decode(self, msg, writer, wlock) -> None:
        rid = msg.get("id")
        codec = int(msg.get("_codec", WIRE_CODEC_JSON))
        # trace propagation (ISSUE 11): the optional wire field becomes a
        # request context whose span id IS the serve.request root span —
        # pre-minted here so the batcher's stage spans parent to it, and
        # recorded at respond time with the client's span as ITS parent
        client_ctx = tracing.TraceContext.from_wire(msg.get(TRACE_FIELD))
        req_ctx = None if client_ctx is None else client_ctx.child()
        t_accept = time.perf_counter()
        if self._draining:
            # refused like every other rejection: a traced request still
            # gets its serve.request span and echoed trace id
            await self._write(writer, wlock, self._rejection(
                rid, RuntimeError("server is draining"),
                req_ctx, client_ctx, t_accept))
            return
        try:
            fut = self.batcher.submit(
                msg["session"],
                np.asarray(msg["syndromes"], dtype=np.uint8),
                tenant=str(msg.get("tenant", "default")),
                request_id=None if rid is None else str(rid),
                trace=req_ctx,
                idem=_wire_idem(msg))
        except AdmissionError as exc:
            # the SLO gate: shed traffic is answered with a structured
            # flag so load generators can tell backpressure from bugs
            await self._write(writer, wlock, self._rejection(
                rid, exc, req_ctx, client_ctx, t_accept,
                shed=True, tenant=exc.tenant, burn_rate=exc.burn_rate))
            return
        except Exception as exc:  # noqa: BLE001 — answered, not dropped
            await self._write(writer, wlock, self._rejection(
                rid, exc, req_ctx, client_ctx, t_accept))
            return
        task = asyncio.ensure_future(
            self._respond(rid, fut, writer, wlock,
                          client_ctx=client_ctx, req_ctx=req_ctx,
                          t_accept=t_accept, codec=codec))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    @staticmethod
    def _rejection(rid, exc, req_ctx, client_ctx, t_accept,
                   **extra) -> dict:
        """Error payload for a request refused at submit.  A TRACED
        rejection still gets its serve.request root span (ok=False) and
        the echoed trace id — the requests an operator most wants to
        find in /tracez are the ones being refused."""
        error = f"{type(exc).__name__}: {exc}"
        payload = {"id": rid, "ok": False, "error": error, **extra}
        if req_ctx is not None:
            payload["trace_id"] = req_ctx.trace_id
            tracing.record_span(
                "serve.request", req_ctx, span_id=req_ctx.span_id,
                parent_id=client_ctx.span_id,
                dur_s=time.perf_counter() - t_accept, ok=False,
                error=error,
                **({} if rid is None else {"request_id": str(rid)}))
        return payload

    async def _respond(self, rid, fut, writer, wlock, *, client_ctx=None,
                       req_ctx=None, t_accept=0.0,
                       codec=WIRE_CODEC_JSON) -> None:
        ok = True
        error = None
        packed = codec == WIRE_CODEC_PACKED
        try:
            res = await asyncio.wrap_future(fut)
            payload = {
                "id": rid, "ok": True,
                # v1 serializes via .tolist() at encode time (native ints,
                # no int64 copy); v2 packs the np planes directly — the
                # response codec mirrors the request's
                "corrections": (res.corrections if packed
                                else res.corrections.tolist()),
                "converged": (None if res.converged is None
                              else [bool(x) for x in res.converged]),
                "latency_ms": round(res.latency_s * 1e3, 3),
            }
        except Exception as exc:  # noqa: BLE001
            ok, error = False, f"{type(exc).__name__}: {exc}"
            packed = False  # errors are structured JSON in every codec
            payload = {"id": rid, "ok": False, "error": error}
        if req_ctx is not None:
            payload["trace_id"] = req_ctx.trace_id
        t_write = time.perf_counter()
        # response-path chaos: the connection dies with the answer already
        # computed but unwritten — the client resubmits on its new
        # connection and the scheduler's answered-LRU replays the result
        # instead of decoding twice (the exactly-once window this site
        # exists to pin)
        if await self._consume_conn_fault(
                lambda on: faultinject.site(
                    "serve_respond",
                    actions={"conn_drop": on, "stall": on}),
                writer, wlock):
            return
        try:
            await self._write(writer, wlock, payload,
                              codec=(WIRE_CODEC_PACKED if packed
                                     else WIRE_CODEC_JSON))
        except (ConnectionError, RuntimeError):
            pass  # client went away; the decode itself completed
        if req_ctx is not None:
            now = time.perf_counter()
            tracing.record_span(
                "respond", req_ctx, dur_s=now - t_write,
                **({} if rid is None else {"request_id": str(rid)}))
            # the request's root span: accept -> response written, with
            # the pre-minted span id the stage spans already parent to,
            # itself parented to the CLIENT's span
            tracing.record_span(
                "serve.request", req_ctx, span_id=req_ctx.span_id,
                parent_id=client_ctx.span_id, dur_s=now - t_accept,
                ok=ok, **({} if error is None else {"error": error}),
                **({} if rid is None else {"request_id": str(rid)}))

    # drain (await transport backpressure) only past this much buffered
    # response data: draining per frame costs an event-loop round-trip
    # per response, which measured as a real serving tax under pipelined
    # windows — the transport buffers small frames and TCP flow control
    # still bounds the total via the high-water mark
    _DRAIN_THRESHOLD = 256 * 1024

    @classmethod
    async def _write(cls, writer, wlock, obj,
                     codec=WIRE_CODEC_JSON) -> None:
        try:
            frame = (encode_response_frame(obj, codec)
                     if codec == WIRE_CODEC_PACKED else encode_frame(obj))
        except ValueError as exc:
            # a response too large for one frame (huge decode batch):
            # answer the request with a structured error instead of
            # killing the connection mid-pipeline
            frame = encode_frame({"id": obj.get("id"), "ok": False,
                                  "error": str(exc)})
        telemetry.count("serve.bytes_tx", len(frame))
        async with wlock:
            writer.write(frame)
            if (writer.transport.get_write_buffer_size()
                    > cls._DRAIN_THRESHOLD):
                await writer.drain()

    # ------------------------------------------------------------------
    async def shutdown(self, drain: bool = True, grace_s: float = 0.25,
                       drain_timeout: float = 60.0) -> None:
        """Stop accepting connections; with ``drain``, serve for a short
        grace window (so request bytes already on the wire still reach the
        batcher), then flush the batcher so every accepted request's
        response is written, and only then close the remaining
        connections.  Requests arriving after the grace window get a
        structured "draining" error response — answered, never silently
        dropped."""
        if self._server is not None:
            # close() stops accepting immediately; wait_closed() is
            # deferred to the END — on Python >= 3.12.1 it also waits for
            # every live connection handler, which are only cancelled
            # below (awaiting it here would deadlock the graceful path
            # while pipelined clients stay connected)
            self._server.close()
        if drain and grace_s:
            await asyncio.sleep(grace_s)
        self._draining = True
        # both paths block (join the dispatcher thread): run off-loop so
        # in-flight response tasks keep streaming.  drain flushes every
        # queued request; the abandon path (drain=False) fails queued
        # futures IMMEDIATELY and stops the worker — without it the
        # response-task gather below would sit out the scheduler's
        # max_wait deadline and the dispatcher thread would leak
        await asyncio.get_running_loop().run_in_executor(
            None, ((lambda: self.batcher.drain(timeout=drain_timeout))
                   if drain else self.batcher.close))
        # retire surviving streams loudly: their watermarks are the last
        # committed cycles, so the accounting trail ends with a close
        for sid, stream in list(self._streams.items()):
            self._streams.pop(sid, None)
            info = stream.close()
            telemetry.event("stream_close", stream=str(sid),
                            committed=info["committed"],
                            committed_cycles=info["committed_cycles"],
                            reason="shutdown")
        if self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)
        for conn in list(self._conns):
            conn.cancel()
        if self._conns:
            await asyncio.gather(*list(self._conns), return_exceptions=True)
        if self._server is not None:
            await self._server.wait_closed()
        if not drain:
            # the drained path already emitted its serve_drain from
            # batcher.drain() (with the real pending/completed counts) —
            # a second event here would double-count shutdowns downstream
            telemetry.event("serve_drain", pending_requests=-1,
                            completed=int(self.batcher.completed))

    async def abort_hard(self) -> None:
        """Die like a killed host (ISSUE 18 ``host_kill`` chaos): stop
        accepting, cancel every response/connection task BEFORE the
        batcher closes — so in-flight requests vanish as TRANSPORT death,
        never as structured error frames (a real power loss writes
        nothing) — and only then tear the batcher down.  Clients must
        recover purely through reconnect + idempotent resubmit against
        the family's successor host."""
        if self._server is not None:
            self._server.close()
        for task in list(self._tasks):
            task.cancel()
        if self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)
        for conn in list(self._conns):
            conn.cancel()
        if self._conns:
            await asyncio.gather(*list(self._conns), return_exceptions=True)
        # the draining flag only flips AFTER every connection is gone: a
        # conn task processing its last frame between our cancel and its
        # next await point must die silently, not answer a structured
        # "draining" refusal — the client would take that as a permanent
        # per-request failure instead of resubmitting to the successor
        self._draining = True
        await asyncio.get_running_loop().run_in_executor(
            None, self.batcher.close)
        # streams die with the host — NO stream_close events: the ledger
        # state survives only through what replication already exported
        self._streams.clear()
        if self._server is not None:
            await self._server.wait_closed()
        telemetry.count("serve.host_kills")


class ServerHandle:
    """A DecodeServer running on its own event-loop thread (what the bench
    and tests use — the caller's thread stays free to drive clients)."""

    def __init__(self, server: DecodeServer, loop: asyncio.AbstractEventLoop,
                 thread: threading.Thread):
        self.server = server
        self._loop = loop
        self._thread = thread

    @property
    def address(self) -> tuple[str, int]:
        return (self.server.host, self.server.port)

    def stop(self, drain: bool = True, timeout: float = 60.0) -> None:
        try:
            # the batcher's drain deadline is the binding one (it raises
            # the informative TimeoutError); the outer wait gets headroom
            # so it cannot fire first and kill a near-deadline drain
            asyncio.run_coroutine_threadsafe(
                self.server.shutdown(drain=drain, drain_timeout=timeout),
                self._loop).result(timeout + 15.0)
        finally:
            # even a failed/timed-out drain must tear the loop thread down
            # — leaving it running would leak the thread and keep client
            # connections open with no one serving them
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=timeout)

    def kill(self, timeout: float = 15.0) -> None:
        """Hard host death (``host_kill`` chaos): no drain, no error
        frames — connections just die.  See ``DecodeServer.abort_hard``."""
        try:
            asyncio.run_coroutine_threadsafe(
                self.server.abort_hard(), self._loop).result(timeout)
        finally:
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=timeout)


def start_server_thread(batcher: ContinuousBatcher, host: str = "127.0.0.1",
                        port: int = 0,
                        stream_profiles: dict | None = None) -> ServerHandle:
    """Start a DecodeServer on a daemon thread; returns once it accepts."""
    server = DecodeServer(batcher, host=host, port=port,
                          stream_profiles=stream_profiles)
    loop, thread = spawn_server_loop(server.start, "qldpc-serve-server",
                                     "decode server")
    return ServerHandle(server, loop, thread)
