"""Decode-as-a-service: persistent sessions + continuous batching + an
asyncio front-end (ISSUE 8 / ROADMAP open item 1).

The offline stack runs sweeps that rebuild device programs per run; this
subsystem turns the same library pieces — value-based decode programs
(decoders.bp_decoders.decode_device), the per-H build memos (ops/bp), the
resilience retry/watchdog layer, the telemetry registry — into a
request-driven decoder service:

  session.py    DecodeSession / SessionCache: AOT-compiled decode programs
                per (H, shape-bucket), persistently cached — warm requests
                perform zero retraces.
  scheduler.py  ContinuousBatcher: coalesces requests across tenants into
                padded megabatches with deadline-aware flush and
                round-robin fairness; graceful drain.
  server.py     asyncio TCP front-end (length-prefixed JSON frames),
                streamed per-request responses, drain-on-shutdown.
  client.py     blocking pipelined client (the bench load generator).
  ops.py        live ops plane (ISSUE 11): SLO burn-rate engine feeding
                shed/defer admission signals into the batcher, plus the
                /metrics /healthz /varz /tracez HTTP sidecar.

Per-request observability (ISSUE 11): trace contexts ride an optional
wire-frame field end to end (utils.tracing) — queue_wait / batch_assemble
/ pad / device_decode / slice / respond stage spans land in the telemetry
JSONL and the always-on flight-recorder ring, which ships a postmortem
when a dispatch dies.

``bench.py serve`` (BENCH_MODE=serve) measures sustained QPS and p50/p99
latency under a mixed-code multi-tenant request storm (plus a tracing
on/off A/B arm); the ``serve.*`` telemetry surface is rendered by
scripts/telemetry_report.py and scripts/sweep_dashboard.py.
"""
from .session import (
    DEFAULT_BUCKETS,
    DecodeOutput,
    DecodeSession,
    SessionCache,
)
from .scheduler import ContinuousBatcher, DecodeResult, assemble_round_robin
from .ops import (
    AdmissionError,
    OpsHandle,
    OpsServer,
    SLOEngine,
    SLOPolicy,
    start_ops_thread,
)
from .server import DecodeServer, ServerHandle, start_server_thread
from .client import ClientResult, DecodeClient

__all__ = [
    "DEFAULT_BUCKETS",
    "DecodeOutput",
    "DecodeSession",
    "SessionCache",
    "ContinuousBatcher",
    "DecodeResult",
    "assemble_round_robin",
    "AdmissionError",
    "OpsHandle",
    "OpsServer",
    "SLOEngine",
    "SLOPolicy",
    "start_ops_thread",
    "DecodeServer",
    "ServerHandle",
    "start_server_thread",
    "ClientResult",
    "DecodeClient",
]
