"""Decode-as-a-service: persistent sessions + continuous batching + an
asyncio front-end (ISSUE 8 / ROADMAP open item 1).

The offline stack runs sweeps that rebuild device programs per run; this
subsystem turns the same library pieces — value-based decode programs
(decoders.bp_decoders.decode_device), the per-H build memos (ops/bp), the
resilience retry/watchdog layer, the telemetry registry — into a
request-driven decoder service:

  session.py    DecodeSession / SessionCache: AOT-compiled decode programs
                per (H, shape-bucket), persistently cached — warm requests
                perform zero retraces; ``heal()`` rebuilds + recompiles in
                the background and swaps atomically (ISSUE 14).
                FusedDecodeGroup (ISSUE 15): one cell-fused program per
                bucket FAMILY (session = cell axis, traced lane_cell), so
                co-bucketed sessions' rounds ride one dispatch; hot
                sessions shard their decode across a mesh
                (``DecodeSession(mesh=)`` + shard()/unshard()).
  wire.py       the wire codec, defined once for both ends: JSON v1 and
                the packed binary v2 (ISSUE 15 — bitplanes in the
                gf2_packed device layout, hello negotiation, v1 clients
                served forever, lint-pinned layout contract).
  scheduler.py  ContinuousBatcher: coalesces requests across tenants into
                padded megabatches with deadline-aware flush and
                round-robin fairness; graceful drain.  Cross-session
                fused rounds (ISSUE 15): co-family pending sessions
                flush into ONE fused dispatch, per-session fallbacks
                counted and reported in health().  Exactly-once
                re-dispatch (ISSUE 14): an idempotency journal dedupes
                resubmits/hedges, failed dispatches re-queue their batch
                (bounded attempts, then a structured error), and every
                failure feeds the self-healing incident stream.
  server.py     asyncio TCP front-end (length-prefixed frames, both
                codecs), streamed per-request responses matched by id,
                drain-on-shutdown; network chaos sites (conn_drop /
                torn_frame); serve.bytes_rx/tx accounting.
  client.py     blocking pipelined client (the bench load generator) with
                codec negotiation at connect, reconnect + resubmit and
                hedged-resubmit transport recovery (ISSUE 14) — broken
                pipes are per-request transient errors, never fatal to
                the client.
  ops.py        live ops plane (ISSUE 11): SLO burn-rate engine feeding
                shed/defer admission signals into the batcher, plus the
                /metrics /healthz /varz /tracez HTTP sidecar; HealthProbe
                (ISSUE 14) — the self-healing loop converting dispatch
                incidents + device-reset epochs into background session
                heals; AutoScaler (ISSUE 15) — the control loop ACTING on
                the admission signals: batch-target resize + mesh
                shard/retire with versioned scale_event telemetry;
                AlertEngine (ISSUE 17) — declarative threshold/deadman
                rules over the utils.timeseries store, evaluated on the
                scrape tick, /alertz + schema-v7 transition events.
  fleet.py      federation gateway (ISSUE 17): scrapes N ops endpoints,
                merges counters bit-exactly / histogram buckets
                additively with per-host labels, re-serves fleet
                /metrics /healthz /alertz; host-down is a deadman alert.
  router.py     multi-host serving fabric (ISSUE 18): FleetRouter places
                sessions on hosts by bucket FAMILY (consistent hash —
                never per session, which would de-fuse the fused
                dispatch), forwards client frames in a routing envelope
                with an epoch fence, incrementally replicates each
                host's answered journal + stream ledgers to the family
                successor, and on the gateway's host-down deadman
                performs the sticky exactly-once handoff (gate, flush
                to the watermark, adopt at epoch+1).  FleetScaler drives
                per-host AutoScalers + live family rebalancing;
                LocalFleet is the in-process N-host harness behind
                ``bench.py fleet`` and the fleet chaos acceptance.

Per-request observability (ISSUE 11): trace contexts ride an optional
wire-frame field end to end (utils.tracing) — queue_wait / batch_assemble
/ pad / device_decode / slice / respond stage spans land in the telemetry
JSONL and the always-on flight-recorder ring, which ships a postmortem
when a dispatch dies.

``bench.py serve`` (BENCH_MODE=serve) measures sustained QPS and p50/p99
latency under a mixed-code multi-tenant request storm (plus a tracing
on/off A/B arm); the ``serve.*`` telemetry surface is rendered by
scripts/telemetry_report.py and scripts/sweep_dashboard.py.
"""
from .session import (
    DEFAULT_BUCKETS,
    DecodeOutput,
    DecodeSession,
    FusedDecodeGroup,
    SessionCache,
    bucket_family,
)
from .scheduler import ContinuousBatcher, DecodeResult, assemble_round_robin
from .ops import (
    AdmissionError,
    AlertEngine,
    AlertRule,
    AutoScaler,
    HealthProbe,
    OpsHandle,
    OpsServer,
    ScalePolicy,
    SLOEngine,
    SLOPolicy,
    default_alert_rules,
    start_ops_thread,
)
from .fleet import FleetGateway, FleetHandle, FleetServer, start_fleet_thread
from .router import (
    FleetRouter,
    FleetScaler,
    HashRing,
    LocalFleet,
    RouterFleetServer,
    RouterHandle,
    start_router_ops_thread,
    start_router_thread,
)
from .server import DecodeServer, ServerHandle, start_server_thread
from .client import ClientResult, DecodeClient

__all__ = [
    "DEFAULT_BUCKETS",
    "DecodeOutput",
    "DecodeSession",
    "FusedDecodeGroup",
    "SessionCache",
    "bucket_family",
    "ContinuousBatcher",
    "DecodeResult",
    "assemble_round_robin",
    "AdmissionError",
    "AlertEngine",
    "AlertRule",
    "AutoScaler",
    "ScalePolicy",
    "HealthProbe",
    "OpsHandle",
    "OpsServer",
    "SLOEngine",
    "SLOPolicy",
    "default_alert_rules",
    "start_ops_thread",
    "FleetGateway",
    "FleetHandle",
    "FleetServer",
    "start_fleet_thread",
    "FleetRouter",
    "FleetScaler",
    "HashRing",
    "LocalFleet",
    "RouterFleetServer",
    "RouterHandle",
    "start_router_ops_thread",
    "start_router_thread",
    "DecodeServer",
    "ServerHandle",
    "start_server_thread",
    "ClientResult",
    "DecodeClient",
]
