"""Decode-as-a-service: persistent sessions + continuous batching + an
asyncio front-end (ISSUE 8 / ROADMAP open item 1).

The offline stack runs sweeps that rebuild device programs per run; this
subsystem turns the same library pieces — value-based decode programs
(decoders.bp_decoders.decode_device), the per-H build memos (ops/bp), the
resilience retry/watchdog layer, the telemetry registry — into a
request-driven decoder service:

  session.py    DecodeSession / SessionCache: AOT-compiled decode programs
                per (H, shape-bucket), persistently cached — warm requests
                perform zero retraces.
  scheduler.py  ContinuousBatcher: coalesces requests across tenants into
                padded megabatches with deadline-aware flush and
                round-robin fairness; graceful drain.
  server.py     asyncio TCP front-end (length-prefixed JSON frames),
                streamed per-request responses, drain-on-shutdown.
  client.py     blocking pipelined client (the bench load generator).

``bench.py serve`` (BENCH_MODE=serve) measures sustained QPS and p50/p99
latency under a mixed-code multi-tenant request storm; the ``serve.*``
telemetry surface is rendered by scripts/telemetry_report.py and
scripts/sweep_dashboard.py.
"""
from .session import (
    DEFAULT_BUCKETS,
    DecodeOutput,
    DecodeSession,
    SessionCache,
)
from .scheduler import ContinuousBatcher, DecodeResult, assemble_round_robin
from .server import DecodeServer, ServerHandle, start_server_thread
from .client import ClientResult, DecodeClient

__all__ = [
    "DEFAULT_BUCKETS",
    "DecodeOutput",
    "DecodeSession",
    "SessionCache",
    "ContinuousBatcher",
    "DecodeResult",
    "assemble_round_robin",
    "DecodeServer",
    "ServerHandle",
    "start_server_thread",
    "ClientResult",
    "DecodeClient",
]
