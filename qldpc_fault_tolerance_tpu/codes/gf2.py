"""GF(2) linear algebra on the host.

Replaces the reference's use of ``ldpc.mod2`` (rank/nullspace, see
reference src/QuantumExanderCodesGene.py:19-20,67) and the GF(2) kernels
hidden inside ``bposd.css_code`` / ``bposd.hgp``.  A bit-packed C++ backend
(qldpc_fault_tolerance_tpu/_native) accelerates the hot entry points when
available; the numpy implementations below are the reference semantics and
the fallback.

All matrices are dense ``uint8`` arrays containing {0,1}.  These routines run
on host, once per code / decode-failure — the per-shot GF(2) syndrome products
run on TPU via ops.gf2_matmul instead.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "to_gf2",
    "rref",
    "rank",
    "nullspace",
    "row_basis",
    "solve",
    "gf2_mul",
    "row_reduce_augmented",
    "pack_bitplane",
    "unpack_bitplane",
]


def to_gf2(a) -> np.ndarray:
    """Coerce an array-like to a uint8 {0,1} matrix (mod 2)."""
    arr = np.asarray(a)
    if arr.dtype != np.uint8:
        arr = np.mod(np.round(arr).astype(np.int64), 2).astype(np.uint8)
    else:
        arr = arr & 1
    return np.ascontiguousarray(arr)


def rref(a, ncols: int | None = None):
    """Row-reduce ``a`` over GF(2).

    Returns ``(r, pivots)`` where ``r`` is the reduced matrix (same shape)
    and ``pivots`` the list of pivot column indices.  Only the first
    ``ncols`` columns are eligible as pivots (used for augmented systems).
    """
    r = to_gf2(a).copy()
    m, n = r.shape
    if ncols is None:
        ncols = n
    pivots: list[int] = []
    row = 0
    for col in range(ncols):
        if row >= m:
            break
        sub = r[row:, col]
        nz = np.nonzero(sub)[0]
        if nz.size == 0:
            continue
        piv = row + nz[0]
        if piv != row:
            r[[row, piv]] = r[[piv, row]]
        # eliminate col from every other row with a 1 there
        mask = r[:, col].astype(bool)
        mask[row] = False
        r[mask] ^= r[row]
        pivots.append(col)
        row += 1
    return r, pivots


def rank(a) -> int:
    """GF(2) rank (reference: ldpc.mod2.rank, src/QuantumExanderCodesGene.py:67)."""
    _, pivots = rref(a)
    return len(pivots)


def nullspace(a) -> np.ndarray:
    """Basis of the right kernel of ``a`` over GF(2), as rows.

    Returns shape ``(n - rank, n)``; empty ``(0, n)`` if full column rank.
    """
    a = to_gf2(a)
    m, n = a.shape
    r, pivots = rref(a)
    free = [c for c in range(n) if c not in set(pivots)]
    basis = np.zeros((len(free), n), dtype=np.uint8)
    for i, fc in enumerate(free):
        basis[i, fc] = 1
        # back-substitute: pivot row j has leading 1 at pivots[j]
        for j, pc in enumerate(pivots):
            if r[j, fc]:
                basis[i, pc] = 1
    return basis


def row_basis(a) -> np.ndarray:
    """A basis (subset of reduced rows) of the row space of ``a``."""
    r, pivots = rref(a)
    return r[: len(pivots)].copy()


def solve(a, b):
    """One solution ``x`` of ``a @ x = b (mod 2)``, or None if inconsistent."""
    a = to_gf2(a)
    b = to_gf2(np.atleast_1d(b)).ravel()
    m, n = a.shape
    aug = np.concatenate([a, b[:, None]], axis=1)
    r, pivots = rref(aug, ncols=n)
    x = np.zeros(n, dtype=np.uint8)
    nrows = len(pivots)
    # inconsistent iff a zero row of A maps to 1 in b
    if np.any(r[nrows:, n]):
        return None
    for i, pc in enumerate(pivots):
        x[pc] = r[i, n]
    return x


def gf2_mul(a, b) -> np.ndarray:
    """Matrix product over GF(2) (host)."""
    a = to_gf2(a)
    b = to_gf2(b)
    return (a.astype(np.int64) @ b.astype(np.int64) % 2).astype(np.uint8)


def pack_bitplane(bits) -> np.ndarray:
    """Host reference for ops.gf2_packed.pack_shots: (B, ...) {0,1} ->
    (ceil(B/32), ...) uint32, shot ``32*w + j`` in bit ``j`` (LSB-first).

    Numpy-only so the device packing layout is pinned by an independent
    implementation (tests/test_gf2_packed.py) and host-side artifacts
    (golden fixtures, packed code caches) need no JAX.
    """
    bits = to_gf2(bits)
    b = bits.shape[0]
    w = -(-b // 32)
    pad = w * 32 - b
    if pad:
        bits = np.concatenate(
            [bits, np.zeros((pad,) + bits.shape[1:], np.uint8)], axis=0)
    x = bits.reshape((w, 32) + bits.shape[1:]).astype(np.uint64)
    shifts = np.arange(32, dtype=np.uint64).reshape(
        (1, 32) + (1,) * (bits.ndim - 1))
    return (x << shifts).sum(axis=1).astype(np.uint32)


def unpack_bitplane(packed, batch_size: int) -> np.ndarray:
    """Inverse of ``pack_bitplane``: (W, ...) uint32 -> (batch_size, ...) u8."""
    packed = np.asarray(packed, dtype=np.uint32)
    w = packed.shape[0]
    shifts = np.arange(32, dtype=np.uint32).reshape(
        (1, 32) + (1,) * (packed.ndim - 1))
    bits = (packed[:, None] >> shifts) & np.uint32(1)
    return bits.reshape((w * 32,) + packed.shape[1:]).astype(np.uint8)[:batch_size]


class IncrementalRowReducer:
    """Maintains an online GF(2) row echelon basis.

    Used to extract logical operators: feed candidate vectors and keep the
    ones that increase the rank (reference behavior of bposd.css_code's
    logical computation, consumed at src/Simulators.py:144,156).
    """

    def __init__(self, n: int):
        self.n = n
        self.rows: list[np.ndarray] = []
        self.pivot_cols: list[int] = []

    def reduce(self, v) -> np.ndarray:
        v = to_gf2(np.atleast_1d(v)).ravel().copy()
        for row, pc in zip(self.rows, self.pivot_cols):
            if v[pc]:
                v ^= row
        return v

    def add(self, v) -> bool:
        """Reduce ``v`` against the basis; add if independent. Returns True if added."""
        v = self.reduce(v)
        nz = np.nonzero(v)[0]
        if nz.size == 0:
            return False
        pc = int(nz[0])
        # keep existing rows reduced against the new row
        for i in range(len(self.rows)):
            if self.rows[i][pc]:
                self.rows[i] = self.rows[i] ^ v
        self.rows.append(v)
        self.pivot_cols.append(pc)
        return True

    @property
    def rank(self) -> int:
        return len(self.rows)


def row_reduce_augmented(a, b):
    """Solve ``x @ a = b`` row-wise for many b: returns coefficients or None per row."""
    a = to_gf2(a)
    b = to_gf2(np.atleast_2d(b))
    sols = []
    for row in b:
        sols.append(solve(a.T, row))
    return sols
