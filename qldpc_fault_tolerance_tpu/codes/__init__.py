from . import gf2
from .css import CssCode, css_logicals
from .codegen import (
    GeneRandGraphsLargeGirthFinal,
    GetClassicalCodeParams,
    QuantumExpanderFromCheckMat,
    improve_girth,
    min_cycle_edges,
    random_biregular_tanner,
    tanner_girth,
)
from .hgp import hgp, rep_code, ring_code, classical_code_distance
from .loaders import (
    load_code,
    load_mat_pair,
    load_npy_pair,
    load_object,
    load_pickle_code,
    save_code,
    save_object,
)

__all__ = [
    "gf2",
    "GeneRandGraphsLargeGirthFinal",
    "GetClassicalCodeParams",
    "QuantumExpanderFromCheckMat",
    "improve_girth",
    "min_cycle_edges",
    "random_biregular_tanner",
    "tanner_girth",
    "CssCode",
    "css_logicals",
    "hgp",
    "rep_code",
    "ring_code",
    "classical_code_distance",
    "load_code",
    "load_mat_pair",
    "load_npy_pair",
    "load_object",
    "load_pickle_code",
    "save_code",
    "save_object",
]
