"""Random biregular classical-code generation with girth optimization.

Replaces the reference's seed-code generator
(src/QuantumExanderCodesGene.py:76-330): random (Δc,Δv)-biregular bipartite
Tanner graphs from a configuration model, repaired to simple graphs, then
improved by girth-raising edge swaps; the surviving seeds feed ``hgp(H, H)``
to build the quantum expander codes (hgp_34_* family).

Design differences from the reference (all host-side, one-time):
  * multi-edge repair is a single uniform double-swap loop (handles any
    multiplicity) instead of separate double/triple-switch passes
    (DSwitch/TSwitch, src/QuantumExanderCodesGene.py:76-178);
  * girth is computed exactly by per-edge BFS, not as the min length of a
    fundamental cycle basis (the reference's ``Girth`` via nx.cycle_basis,
    :26-28, can overestimate);
  * the swap acceptance signal counts edges on shortest cycles rather than
    basis cycles — same hill-climbing structure
    (RandSwapEdges1, :268-310), exact signal;
  * everything takes an explicit ``numpy.random.Generator`` so regenerated
    code families are reproducible (recorded seeds).
"""
from __future__ import annotations

from collections import deque

import numpy as np

from . import gf2
from .hgp import classical_code_distance, hgp

__all__ = [
    "random_biregular_tanner",
    "tanner_girth",
    "min_cycle_edges",
    "improve_girth",
    "GeneRandGraphsLargeGirthFinal",
    "GetClassicalCodeParams",
    "QuantumExpanderFromCheckMat",
]

NO_CYCLE = int(1e7)  # the reference's "forest" sentinel (src:28)


def random_biregular_tanner(n0: int, delta_c: int, delta_v: int, rng=None):
    """Random simple (Δc,Δv)-biregular bipartite check matrix.

    Shape: (n0*delta_v) checks x (n0*delta_c) bits — every check has degree
    delta_c, every bit degree delta_v (configuration-model pairing + repair,
    reference RandomaGraphs, src/QuantumExanderCodesGene.py:181-233).
    """
    rng = np.random.default_rng(rng)
    m, n = n0 * delta_v, n0 * delta_c
    while True:
        c_ports = np.repeat(np.arange(m), delta_c)
        v_ports = np.repeat(np.arange(n), delta_v)
        rng.shuffle(c_ports)
        rng.shuffle(v_ports)
        edges = list(zip(c_ports.tolist(), v_ports.tolist()))
        if _repair_multiedges(edges, rng):
            H = np.zeros((m, n), dtype=np.uint8)
            cs, vs = zip(*edges)
            H[list(cs), list(vs)] = 1
            assert (H.sum(1) == delta_c).all() and (H.sum(0) == delta_v).all()
            return H


def _repair_multiedges(edges: list, rng, max_tries: int = 10000) -> bool:
    """Make the multigraph simple by double swaps: replace a duplicated edge
    (c,v) and a random edge (c',v') with (c,v') and (c',v) when that creates
    no new duplicate.  Returns False if it cannot converge (caller redraws)."""
    from collections import Counter

    count = Counter(edges)
    for _ in range(max_tries):
        dups = [e for e, k in count.items() if k > 1]
        if not dups:
            edges[:] = list(count.keys())
            return True
        c, v = dups[0]
        c2, v2 = edges[rng.integers(len(edges))]
        if c2 == c or v2 == v:
            continue
        if count[(c, v2)] or count[(c2, v)]:
            continue
        for old, new in (((c, v), (c, v2)), ((c2, v2), (c2, v))):
            count[old] -= 1
            if not count[old]:
                del count[old]
            count[new] = count.get(new, 0) + 1
        edges[:] = [e for e, k in count.items() for _ in range(k)]
    return False


def _adjacency(H):
    """Tanner-graph adjacency: checks are nodes [0,m), bits [m, m+n)."""
    H = np.asarray(H)
    m, n = H.shape
    adj = [[] for _ in range(m + n)]
    for c, v in zip(*np.nonzero(H)):
        adj[c].append(m + v)
        adj[m + v].append(int(c))
    return adj


def _shortest_cycle_through_edge(adj, u, v) -> int:
    """Length of the shortest cycle containing edge (u,v): 1 + shortest
    path u->v avoiding that edge (BFS)."""
    dist = {u: 0}
    dq = deque([u])
    while dq:
        x = dq.popleft()
        for y in adj[x]:
            if x == u and y == v:
                continue
            if y not in dist:
                dist[y] = dist[x] + 1
                if y == v:
                    return dist[y] + 1
                dq.append(y)
    return NO_CYCLE


def min_cycle_edges(H):
    """(girth, edges-on-a-shortest-cycle) — exact, via per-edge BFS."""
    H = np.asarray(H)
    m, _ = H.shape
    adj = _adjacency(H)
    lengths = {}
    for c, v in zip(*np.nonzero(H)):
        lengths[(int(c), int(v))] = _shortest_cycle_through_edge(adj, int(c), m + int(v))
    girth = min(lengths.values(), default=NO_CYCLE)
    if girth >= NO_CYCLE:
        return NO_CYCLE, []
    return girth, [e for e, L in lengths.items() if L == girth]


def tanner_girth(H) -> int:
    """Exact girth of the Tanner graph (reference Girth, src:26-28 —
    but exact rather than a cycle-basis upper bound)."""
    return min_cycle_edges(H)[0]


def improve_girth(H, target_girth: int, max_iter: int = 20000, rng=None):
    """Hill-climb edge swaps to raise the girth (reference RandSwapEdges1,
    src/QuantumExanderCodesGene.py:268-310): swap a random shortest-cycle
    edge with a random other edge; accept when (girth, -#shortest-cycle
    edges) does not get worse.  Degree sequence is invariant under swaps.

    Returns (H, success)."""
    rng = np.random.default_rng(rng)
    H = np.asarray(H).copy()
    girth, crit = min_cycle_edges(H)
    for _ in range(max_iter):
        if girth >= target_girth:
            return H, True
        c1, v1 = crit[rng.integers(len(crit))]
        es = np.transpose(np.nonzero(H))
        c2, v2 = es[rng.integers(len(es))]
        if (c1, v1) == (int(c2), int(v2)):
            continue
        # swap to (c1,v2), (c2,v1); skip if it would create a duplicate
        if H[c1, v2] or H[c2, v1]:
            continue
        H2 = H.copy()
        H2[c1, v1] = H2[c2, v2] = 0
        H2[c1, v2] = H2[c2, v1] = 1
        g2, crit2 = min_cycle_edges(H2)
        if g2 > girth or (g2 == girth and len(crit2) <= len(crit)):
            H, girth, crit = H2, g2, crit2
    return H, girth >= target_girth


def GeneRandGraphsLargeGirthFinal(n0: int, Delta_c: int, Delta_v: int,
                                  min_girth1: int, target_girth: int,
                                  num: int, max_iter: int, seed=None,
                                  swap_iters: int = 20000):
    """Generate ``num`` (Δc,Δv)-biregular check matrices whose Tanner girth
    reaches ``target_girth`` (reference src/QuantumExanderCodesGene.py:314-330;
    returns check matrices rather than nx graphs)."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(int(max_iter)):
        if len(out) >= num:
            break
        H = random_biregular_tanner(n0, Delta_c, Delta_v, rng)
        if tanner_girth(H) < min_girth1:
            continue
        H2, ok = improve_girth(H, target_girth, max_iter=swap_iters, rng=rng)
        if ok:
            out.append(H2)
    if len(out) < num:
        # non-convergence is a signal, not stdout noise: warn + count it
        import warnings

        from ..utils import telemetry

        telemetry.count("codegen.max_iter_reached")
        warnings.warn(
            f"GeneRandGraphsLargeGirthFinal: max_iter={max_iter} reached "
            f"with {len(out)}/{num} codes at girth {target_girth}",
            stacklevel=2)
    return out


def GetClassicalCodeParams(H):
    """[n, k, d, lambda_2] (reference src/QuantumExanderCodesGene.py:65-73):
    block length, dimension by rank-nullity, exhaustive distance, and the
    second-largest eigenvalue of H^T H (expansion proxy)."""
    H = gf2.to_gf2(H)
    n = H.shape[1]
    k = n - gf2.rank(H)
    d = classical_code_distance(H)
    eigs = np.linalg.eigvalsh(H.T.astype(float) @ H.astype(float))
    lambda_2 = np.sort(eigs)[-2] if len(eigs) >= 2 else 0.0
    return [n, k, d, lambda_2]


def QuantumExpanderFromCheckMat(H, compute_distance: bool = True):
    """hgp(H, H) (reference src/QuantumExanderCodesGene.py:30-34)."""
    return hgp(H, H, compute_distance=compute_distance)
