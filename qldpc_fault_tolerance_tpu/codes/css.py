"""CSS code objects.

Replaces ``bposd.css.css_code`` / ``bposd.hgp.hgp`` instances.  The simulators
touch exactly the attributes ``.N, .K, .hx, .hz, .lx, .lz`` (reference
src/Simulators.py:79-80,127-156), so that is the stable contract here.

Unlike the reference (which mutates shared code objects to swap X/Z sectors,
src/Simulators.py:390-402), CssCode is treated as immutable by the TPU
engines; the compat layer reproduces the mutating behavior where notebooks
rely on it.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from . import gf2

__all__ = ["CssCode", "css_logicals"]


def css_logicals(hx: np.ndarray, hz: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Compute logical operator bases (lx, lz) for a CSS code.

    lx: basis of ker(hz) / rowspace(hx)  (X-logicals commute with Z checks)
    lz: basis of ker(hx) / rowspace(hz)

    Any basis of the quotient is valid for the failure checks the simulators
    perform (residual in rowspace tests at src/Simulators.py:141-156); no
    symplectic pairing is required.
    """
    hx = gf2.to_gf2(hx)
    hz = gf2.to_gf2(hz)
    n = hx.shape[1]
    assert hz.shape[1] == n

    def quotient_basis(ker_of: np.ndarray, im_of: np.ndarray) -> np.ndarray:
        ker = gf2.nullspace(ker_of)
        red = gf2.IncrementalRowReducer(n)
        for row in gf2.row_basis(im_of):
            red.add(row)
        logs = []
        for v in ker:
            if red.add(v):
                logs.append(red.rows[-1])
        if not logs:
            return np.zeros((0, n), dtype=np.uint8)
        return np.stack(logs).astype(np.uint8)

    lx = quotient_basis(hz, hx)
    lz = quotient_basis(hx, hz)
    assert lx.shape[0] == lz.shape[0]
    return lx, lz


@dataclasses.dataclass
class CssCode:
    """A CSS quantum code with the attribute contract of bposd's css_code.

    Attributes
    ----------
    hx, hz : (mx, n), (mz, n) uint8 parity-check matrices
    lx, lz : (K, n) uint8 logical operator bases
    """

    hx: np.ndarray
    hz: np.ndarray
    lx: np.ndarray = None
    lz: np.ndarray = None
    name: str = ""
    D: int | None = None  # distance, when known

    def __post_init__(self):
        self.hx = gf2.to_gf2(self.hx)
        self.hz = gf2.to_gf2(self.hz)
        if self.hx.shape[1] != self.hz.shape[1]:
            raise ValueError(
                f"hx and hz must act on the same qubits: {self.hx.shape} vs {self.hz.shape}"
            )
        comm = gf2.gf2_mul(self.hx, self.hz.T)
        if comm.any():
            raise ValueError("hx @ hz.T != 0 (mod 2): not a valid CSS code")
        if self.lx is None or self.lz is None:
            self.lx, self.lz = css_logicals(self.hx, self.hz)
        else:
            self.lx = gf2.to_gf2(self.lx)
            self.lz = gf2.to_gf2(self.lz)

    @property
    def N(self) -> int:
        return int(self.hx.shape[1])

    @property
    def K(self) -> int:
        return int(self.lx.shape[0])

    def __repr__(self):
        tag = f" {self.name!r}" if self.name else ""
        return f"CssCode{tag}[[{self.N},{self.K}{',' + str(self.D) if self.D else ''}]]"

    def validate(self) -> None:
        """Assert the full CSS contract (used by tests)."""
        assert not gf2.gf2_mul(self.hx, self.hz.T).any()
        assert not gf2.gf2_mul(self.hx, self.lz.T).any(), "lz must commute with hx"
        assert not gf2.gf2_mul(self.hz, self.lx.T).any(), "lx must commute with hz"
        n, k = self.N, self.K
        assert k == n - gf2.rank(self.hx) - gf2.rank(self.hz)
        # lx rows independent of rowspace(hx)
        red = gf2.IncrementalRowReducer(n)
        for row in self.hx:
            red.add(row)
        for row in self.lx:
            assert red.add(row), "lx row lies in rowspace(hx)"
        red = gf2.IncrementalRowReducer(n)
        for row in self.hz:
            red.add(row)
        for row in self.lz:
            assert red.add(row), "lz row lies in rowspace(hz)"
