"""Loaders for the code assets in the reference's ``codes_lib/``.

The reference persists codes as pickled bposd.hgp objects, ``.mat`` Hx/Hz
pairs, and ``.npy``/``.txt`` matrices (reference src/Simulators.py:65-71 and
notebook cells).  The pickles reference bposd classes; ``load_pickle_code``
unpickles them without bposd installed by shimming the class lookup and then
rebuilding a CssCode from the stored arrays.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from . import gf2
from .css import CssCode

__all__ = [
    "load_pickle_code",
    "load_mat_pair",
    "load_npy_pair",
    "save_code",
    "load_code",
    "load_object",
    "save_object",
]


class _Shim:
    """Absorbs the state of any unpicklable class instance."""

    def __init__(self, *a, **k):
        pass


class _PermissiveUnpickler(pickle.Unpickler):
    """Shims unresolvable classes — and classes the compat layer stubs with
    *functions* (bposd.hgp.hgp): NEWOBJ needs a type.  Stub packages are
    recognized by the ``__qldpc_stub__`` marker compat.install() sets on
    them (single source of truth); everything else resolvable passes
    through untouched (numpy's ``_reconstruct`` is a function legitimately
    used via REDUCE and must not be shimmed)."""

    @staticmethod
    def _is_stub_module(module: str) -> bool:
        import sys as _sys

        top = _sys.modules.get(module.split(".")[0])
        return bool(getattr(top, "__qldpc_stub__", False))

    def find_class(self, module, name):
        try:
            obj = super().find_class(module, name)
        except Exception:
            obj = None
        if obj is not None and (
            isinstance(obj, type) or not self._is_stub_module(module)
        ):
            return obj
        return type(name, (_Shim,), {"__module__": module})


def load_object(filename: str):
    """Reference-compatible load_object (src/Simulators.py:69-71), tolerant of
    missing third-party modules inside the pickle."""
    with open(filename, "rb") as f:
        return _PermissiveUnpickler(f).load()


def save_object(obj, filename: str) -> None:
    """Reference-compatible save_object (src/Simulators.py:65-67)."""
    with open(filename, "wb") as f:
        pickle.dump(obj, f, pickle.HIGHEST_PROTOCOL)


def load_pickle_code(path: str) -> CssCode:
    """Load a pickled code object (e.g. codes_lib/hgp_34_n225.pkl) into a CssCode."""
    obj = load_object(path)
    d = obj if isinstance(obj, dict) else obj.__dict__
    kwargs = {}
    for key in ("hx", "hz", "lx", "lz"):
        v = d.get(key)
        if v is None:
            continue
        if hasattr(v, "toarray"):
            v = v.toarray()
        kwargs[key] = gf2.to_gf2(v)
    code = CssCode(name=os.path.splitext(os.path.basename(path))[0], **kwargs)
    if "D" in d and d["D"] is not None:
        try:
            code.D = int(d["D"])
        except (TypeError, ValueError):
            pass
    return code


def _mat_matrix(path: str) -> np.ndarray:
    from scipy.io import loadmat

    data = loadmat(path)
    keys = [k for k in data if not k.startswith("__")]
    if len(keys) != 1:
        raise ValueError(f"expected one matrix in {path}, found keys {keys}")
    m = data[keys[0]]
    if hasattr(m, "toarray"):
        m = m.toarray()
    return gf2.to_gf2(m)


def load_mat_pair(hx_path: str, hz_path: str | None = None, name: str = "") -> CssCode:
    """Load an Hx/Hz ``.mat`` pair (GB codes A1-A4, LP codes; notebook cells 7-8)."""
    if hz_path is None:
        if "_hx" not in hx_path:
            raise ValueError("cannot infer hz path")
        hz_path = hx_path.replace("_hx", "_hz")
    hx = _mat_matrix(hx_path)
    hz = _mat_matrix(hz_path)
    if not name:
        name = os.path.basename(hx_path).replace("_hx.mat", "")
    return CssCode(hx=hx, hz=hz, name=name)


def load_npy_pair(hx_path: str, hz_path: str | None = None, name: str = "") -> CssCode:
    """Load an Hx/Hz ``.npy`` pair (tanner_code1)."""
    if hz_path is None:
        hz_path = hx_path.replace("_hx", "_hz")
    hx = gf2.to_gf2(np.load(hx_path))
    hz = gf2.to_gf2(np.load(hz_path))
    if not name:
        name = os.path.basename(hx_path).replace("_hx.npy", "")
    return CssCode(hx=hx, hz=hz, name=name)


def save_code(code: CssCode, path: str) -> None:
    """Persist a CssCode as .npz (our native format; avoids pickle fragility)."""
    np.savez_compressed(
        path,
        hx=code.hx,
        hz=code.hz,
        lx=code.lx,
        lz=code.lz,
        name=np.array(code.name),
        D=np.array(-1 if code.D is None else code.D),
    )


def load_code(path: str) -> CssCode:
    """Load a CssCode: dispatches on extension (.npz/.pkl/.mat/.npy)."""
    if path.endswith(".npz"):
        data = np.load(path, allow_pickle=False)
        code = CssCode(
            hx=data["hx"], hz=data["hz"], lx=data["lx"], lz=data["lz"],
            name=str(data["name"]),
        )
        d = int(data["D"])
        code.D = None if d < 0 else d
        return code
    if path.endswith(".pkl"):
        return load_pickle_code(path)
    if path.endswith(".mat"):
        return load_mat_pair(path)
    if path.endswith(".npy"):
        return load_npy_pair(path)
    raise ValueError(f"unknown code format: {path}")
