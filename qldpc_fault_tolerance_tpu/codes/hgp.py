"""Hypergraph-product code construction.

Replaces ``bposd.hgp.hgp`` (used at reference src/QuantumExanderCodesGene.py:30-34
and throughout the notebooks).  Convention (verified bit-exact against the
shipped ``codes_lib/hgp_34_n225.pkl``, which stores its seed ``h1``):

    hx = [ h1 (x) I_n2  |  I_m1 (x) h2^T ]
    hz = [ I_n1 (x) h2  |  h1^T (x) I_m2 ]

with qubits ordered (n1*n2 "primal" block, m1*m2 "dual" block).
"""
from __future__ import annotations

import numpy as np

from . import gf2
from .css import CssCode

__all__ = ["hgp", "ring_code", "rep_code", "classical_code_distance"]


def hgp(h1, h2, compute_distance: bool = False, name: str = "") -> CssCode:
    """Hypergraph product of two classical parity-check matrices."""
    h1 = gf2.to_gf2(h1)
    h2 = gf2.to_gf2(h2)
    m1, n1 = h1.shape
    m2, n2 = h2.shape

    hx = np.concatenate(
        [np.kron(h1, np.eye(n2, dtype=np.uint8)), np.kron(np.eye(m1, dtype=np.uint8), h2.T)],
        axis=1,
    )
    hz = np.concatenate(
        [np.kron(np.eye(n1, dtype=np.uint8), h2), np.kron(h1.T, np.eye(m2, dtype=np.uint8))],
        axis=1,
    )
    code = CssCode(hx=hx, hz=hz, name=name)
    if compute_distance:
        code.D = _hgp_distance_upper_bound(code)
    return code


def _hgp_distance_upper_bound(code: CssCode) -> int:
    """Cheap distance estimate: min weight over logical representatives
    reduced by stabilizer rows (upper bound; exact for small codes is done
    via classical_code_distance of the seeds)."""
    best = code.N
    for l, h in ((code.lx, code.hx), (code.lz, code.hz)):
        for row in l:
            w = int(row.sum())
            # greedy weight reduction by stabilizer additions
            cur = row.copy()
            improved = True
            while improved:
                improved = False
                for s in h:
                    cand = cur ^ s
                    if cand.sum() < cur.sum():
                        cur = cand
                        improved = True
            best = min(best, int(cur.sum()), w)
    return best


def rep_code(d: int) -> np.ndarray:
    """(d-1) x d repetition-code parity-check matrix (ldpc.codes.rep_code)."""
    h = np.zeros((d - 1, d), dtype=np.uint8)
    for i in range(d - 1):
        h[i, i] = 1
        h[i, i + 1] = 1
    return h


def ring_code(d: int) -> np.ndarray:
    """d x d closed-loop repetition code (ldpc.codes.ring_code; used for
    toric/surface constructions in the notebooks, e.g. hgp(ring_code(3), ring_code(3)))."""
    h = np.zeros((d, d), dtype=np.uint8)
    for i in range(d):
        h[i, i] = 1
        h[i, (i + 1) % d] = 1
    return h


def classical_code_distance(h, max_k: int = 22) -> int:
    """Exhaustive minimum distance of the classical code ker(h).

    Replaces ldpc.code_util.compute_code_distance
    (reference src/QuantumExanderCodesGene.py:68).  Exponential in k; refuses
    beyond ``max_k``.
    """
    ker = gf2.nullspace(h)
    k, n = ker.shape
    if k == 0:
        return int(1e9)  # matches ldpc convention of "no codewords"
    if k > max_k:
        raise ValueError(f"k={k} too large for exhaustive distance")
    best = n + 1
    # enumerate non-zero combinations via gray-code accumulation
    cur = np.zeros(n, dtype=np.uint8)
    prev_gray = 0
    for i in range(1, 2**k):
        gray = i ^ (i >> 1)
        changed = (gray ^ prev_gray).bit_length() - 1
        prev_gray = gray
        cur = cur ^ ker[changed]
        w = int(cur.sum())
        if 0 < w < best:
            best = w
    return best
