// Ordered-statistics decoding (OSD) over GF(2), host-side.
//
// TPU-native replacement for the OSD stage of bposd.bposd_decoder
// (reference src/Decoders.py:24-41): BP runs on TPU; only the minority of
// shots whose BP output fails to match the syndrome are post-processed here.
//
// Methods (mirroring bposd's osd_method):
//   0 = OSD-0           : solve on the most-error-likely information set
//   1 = OSD-E (order w) : exhaustive 2^w search over the w most suspect
//                         non-pivot columns
//   2 = OSD-CS (order w): "combination sweep" — all weight-1 patterns over
//                         the non-pivot columns plus all weight-2 patterns
//                         within the first w
//
// Candidates are scored by the weighted (log-likelihood) error cost, so the
// winner is the most probable error consistent with the syndrome — this is
// bposd's "osdw" output (osdw_decoding, src/Decoders.py:41).
//
// Representation: the permuted parity-check matrix is bit-packed row-major
// (uint64 words). Gaussian elimination produces U*H_pi in reduced form; each
// candidate solve is then an XOR accumulation over free-column bit vectors.
//
// Threading: shots are independent; a simple atomic work queue fans them out
// across std::thread workers.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

using u64 = uint64_t;

struct BitMat {
  int rows = 0, cols = 0, words = 0;
  std::vector<u64> data;  // row-major, words per row
  void init(int r, int c) {
    rows = r;
    cols = c;
    words = (c + 63) / 64;
    data.assign(static_cast<size_t>(r) * words, 0);
  }
  u64* row(int i) { return data.data() + static_cast<size_t>(i) * words; }
  const u64* row(int i) const {
    return data.data() + static_cast<size_t>(i) * words;
  }
  void set(int i, int j) { row(i)[j >> 6] |= (u64(1) << (j & 63)); }
  bool get(int i, int j) const {
    return (row(i)[j >> 6] >> (j & 63)) & 1;
  }
  void xor_rows(int dst, int src) {
    u64* d = row(dst);
    const u64* s = row(src);
    for (int w = 0; w < words; ++w) d[w] ^= s[w];
  }
};

// One decode workspace, reused across shots by a worker thread.
struct OsdWorker {
  int m, n;
  const uint8_t* H;            // m*n row-major {0,1}
  const double* channel_cost;  // n: signed log((1-p)/p) cost of flipping bit j
                               // (negative when a prior exceeds 1/2)

  std::vector<int> order;      // column permutation (most suspect first)
  std::vector<int> pivot_cols; // permuted indices chosen as pivots (size r)
  std::vector<int> free_cols;  // permuted indices not chosen (size n-r)
  BitMat R;                    // m x n reduced permuted matrix
  std::vector<uint8_t> u;      // reduced syndrome (m)
  std::vector<uint8_t> e_perm; // candidate error in permuted coords (n)

  void sort_columns(const double* llr) {
    order.resize(n);
    for (int j = 0; j < n; ++j) order[j] = j;
    // most likely in error first = smallest posterior LLR first
    std::stable_sort(order.begin(), order.end(),
                     [&](int a, int b) { return llr[a] < llr[b]; });
  }

  // Gaussian elimination over the permuted columns; returns rank.
  int eliminate(const uint8_t* synd) {
    R.init(m, n);
    for (int i = 0; i < m; ++i)
      for (int jj = 0; jj < n; ++jj)
        if (H[static_cast<size_t>(i) * n + order[jj]]) R.set(i, jj);
    u.assign(synd, synd + m);

    pivot_cols.clear();
    free_cols.clear();
    std::vector<char> is_pivot(n, 0);
    int r = 0;
    for (int col = 0; col < n && r < m; ++col) {
      int piv = -1;
      for (int i = r; i < m; ++i)
        if (R.get(i, col)) {
          piv = i;
          break;
        }
      if (piv < 0) continue;
      if (piv != r) {
        for (int w = 0; w < R.words; ++w) std::swap(R.row(r)[w], R.row(piv)[w]);
        std::swap(u[r], u[piv]);
      }
      for (int i = 0; i < m; ++i) {
        if (i != r && R.get(i, col)) {
          R.xor_rows(i, r);
          u[i] ^= u[r];
        }
      }
      pivot_cols.push_back(col);
      is_pivot[col] = 1;
      ++r;
    }
    for (int col = 0; col < n; ++col)
      if (!is_pivot[col]) free_cols.push_back(col);
    return r;
  }

  double solution_cost(const std::vector<uint8_t>& e_s,
                       const std::vector<int>& t_bits) const {
    double c = 0.0;
    int r = static_cast<int>(pivot_cols.size());
    for (int i = 0; i < r; ++i)
      if (e_s[i]) c += channel_cost[order[pivot_cols[i]]];
    for (int fj : t_bits) c += channel_cost[order[free_cols[fj]]];
    return c;
  }

  // e_s[i] = u[i] xor sum_{fj in t_bits} R[i][free_cols[fj]] for pivot rows.
  void solve_pivots(const std::vector<int>& t_bits,
                    std::vector<uint8_t>& e_s) const {
    int r = static_cast<int>(pivot_cols.size());
    e_s.assign(r, 0);
    for (int i = 0; i < r; ++i) e_s[i] = u[i];
    for (int fj : t_bits) {
      int col = free_cols[fj];
      for (int i = 0; i < r; ++i) e_s[i] ^= R.get(i, col);
    }
  }

  void emit(const std::vector<uint8_t>& e_s, const std::vector<int>& t_bits,
            uint8_t* out) {
    std::memset(out, 0, n);
    int r = static_cast<int>(pivot_cols.size());
    for (int i = 0; i < r; ++i)
      if (e_s[i]) out[order[pivot_cols[i]]] = 1;
    for (int fj : t_bits) out[order[free_cols[fj]]] = 1;
  }

  void decode(const uint8_t* synd, const double* llr, int method, int osd_order,
              uint8_t* out) {
    sort_columns(llr);
    eliminate(synd);
    int r = static_cast<int>(pivot_cols.size());
    int nfree = static_cast<int>(free_cols.size());

    std::vector<uint8_t> best_es, cand_es;
    std::vector<int> best_t, cand_t;
    solve_pivots({}, best_es);
    double best_cost = solution_cost(best_es, {});

    auto consider = [&](const std::vector<int>& t_bits) {
      solve_pivots(t_bits, cand_es);
      double c = solution_cost(cand_es, t_bits);
      if (c < best_cost) {
        best_cost = c;
        best_es = cand_es;
        best_t = t_bits;
      }
    };

    if (method == 1) {  // OSD-E: all 2^w patterns on first w free cols
      int w = std::min(osd_order, nfree);
      if (w > 20) w = 20;  // safety bound: 2^20 candidates
      for (long pat = 1; pat < (1L << w); ++pat) {
        cand_t.clear();
        for (int b = 0; b < w; ++b)
          if ((pat >> b) & 1) cand_t.push_back(b);
        consider(cand_t);
      }
    } else if (method == 2) {  // OSD-CS: weight-1 sweep + weight-2 in first w
      for (int b = 0; b < nfree; ++b) consider({b});
      int w = std::min(osd_order, nfree);
      for (int a = 0; a < w; ++a)
        for (int b = a + 1; b < w; ++b) consider({a, b});
    }
    (void)r;
    emit(best_es, best_t, out);
  }
};

}  // namespace

extern "C" {

// Batched OSD decode. Returns 0 on success.
//   H            : m*n row-major {0,1}
//   syndromes    : batch*m
//   posterior_llr: batch*n (soft BP output; ordering key)
//   channel_cost : n (signed log((1-p)/p); candidate scoring)
//   method       : 0 osd0, 1 osd_e, 2 osd_cs
//   out          : batch*n error estimates
int qldpc_osd_decode_batch(const uint8_t* H, int m, int n,
                           const uint8_t* syndromes, const double* posterior_llr,
                           int batch, const double* channel_cost, int method,
                           int osd_order, int nthreads, uint8_t* out) {
  if (m <= 0 || n <= 0 || batch < 0) return 1;
  if (batch == 0) return 0;
  if (nthreads <= 0) nthreads = static_cast<int>(std::thread::hardware_concurrency());
  nthreads = std::max(1, std::min(nthreads, batch));

  std::atomic<int> next(0);
  auto work = [&]() {
    OsdWorker w;
    w.m = m;
    w.n = n;
    w.H = H;
    w.channel_cost = channel_cost;
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= batch) break;
      w.decode(syndromes + static_cast<size_t>(i) * m,
               posterior_llr + static_cast<size_t>(i) * n, method, osd_order,
               out + static_cast<size_t>(i) * n);
    }
  };

  if (nthreads == 1) {
    work();
  } else {
    std::vector<std::thread> threads;
    for (int t = 0; t < nthreads; ++t) threads.emplace_back(work);
    for (auto& t : threads) t.join();
  }
  return 0;
}

// GF(2) rank of an m x n {0,1} matrix (utility for the codes layer).
int qldpc_gf2_rank(const uint8_t* H, int m, int n) {
  BitMat M;
  M.init(m, n);
  for (int i = 0; i < m; ++i)
    for (int j = 0; j < n; ++j)
      if (H[static_cast<size_t>(i) * n + j]) M.set(i, j);
  int r = 0;
  for (int col = 0; col < n && r < m; ++col) {
    int piv = -1;
    for (int i = r; i < m; ++i)
      if (M.get(i, col)) {
        piv = i;
        break;
      }
    if (piv < 0) continue;
    if (piv != r)
      for (int w = 0; w < M.words; ++w) std::swap(M.row(r)[w], M.row(piv)[w]);
    for (int i = r + 1; i < m; ++i)
      if (M.get(i, col)) M.xor_rows(i, r);
    ++r;
  }
  return r;
}

}  // extern "C"
