"""Native (C++) host kernels: OSD post-processing and GF(2) elimination.

Built on first use with g++ into a shared library next to the sources; loaded
via ctypes (no pybind11 dependency).  ``load_native()`` returns None if the
toolchain is unavailable, in which case callers fall back to the numpy
implementations in decoders/osd.py.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "osd.cpp")
_LIB = os.path.join(_HERE, "libqldpc_native.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    cmd = [
        "g++",
        "-O3",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-pthread",
        _SRC,
        "-o",
        _LIB,
    ]
    try:
        res = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if res.returncode != 0:
            import warnings

            warnings.warn(f"native build failed:\n{res.stderr[-2000:]}")
            return False
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False


def load_native():
    """Return the loaded ctypes library, building it if necessary (or None)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        c_u8p = ctypes.POINTER(ctypes.c_uint8)
        c_dp = ctypes.POINTER(ctypes.c_double)
        lib.qldpc_osd_decode_batch.argtypes = [
            c_u8p, ctypes.c_int, ctypes.c_int,       # H, m, n
            c_u8p, c_dp, ctypes.c_int,               # syndromes, posterior_llr, batch
            c_dp, ctypes.c_int, ctypes.c_int,        # channel_cost, method, osd_order
            ctypes.c_int, c_u8p,                     # nthreads, out
        ]
        lib.qldpc_osd_decode_batch.restype = ctypes.c_int
        lib.qldpc_gf2_rank.argtypes = [c_u8p, ctypes.c_int, ctypes.c_int]
        lib.qldpc_gf2_rank.restype = ctypes.c_int
        _lib = lib
        return _lib
