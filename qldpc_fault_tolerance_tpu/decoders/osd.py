"""Ordered-statistics decoding (host post-processing stage of BP+OSD).

``bposd.bposd_decoder`` semantics (reference src/Decoders.py:26-41): run BP;
if BP's hard decision already satisfies the syndrome, return it; otherwise run
OSD seeded by BP's soft output and return the most probable consistent error
("osdw" weighting).  Here BP runs batched on TPU (ops/bp.py) and only the
non-converged shots are gathered back to host for OSD — GF(2) elimination is
inherently sequential, so it lives in C++ (_native/osd.cpp) with a numpy
fallback.
"""
from __future__ import annotations

import numpy as np

from .._native import load_native
from ..codes import gf2

__all__ = ["osd_decode_batch", "osd_postprocess", "OSD_CS_MAX_ORDER"]

_METHODS = {"osd_0": 0, "osd0": 0, "osd_e": 1, "osd_cs": 2, "exhaustive": 1}

#: Shared order cap for the reprocessing stages — OSD-E's candidate count
#: is 2^order and OSD-CS's pair block is order^2/2, so an uncapped order
#: is a resource bug, not a knob.  ONE constant used by the host paths
#: here, the device OSD-E scorer (ops/osd_device.py) and the device CS
#: sweep (ops/osd_cs_device.py); entry points raise a loud ValueError
#: above it instead of silently clamping (the C++ keeps its own internal
#: 2^20 safety bound).
OSD_CS_MAX_ORDER = 20


def _check_osd_order(osd_order: int) -> int:
    order = int(osd_order)
    if order > OSD_CS_MAX_ORDER:
        raise ValueError(
            f"osd_order={order} exceeds OSD_CS_MAX_ORDER="
            f"{OSD_CS_MAX_ORDER} — candidate counts grow as 2^order "
            f"(OSD-E) / order^2 (OSD-CS); raise decoders.osd."
            f"OSD_CS_MAX_ORDER deliberately rather than relying on a "
            f"silent clamp")
    return order


def _channel_cost(channel_probs: np.ndarray) -> np.ndarray:
    """Signed per-bit cost log((1-p)/p) of setting a bit in the candidate.

    Kept signed: a channel prior > 1/2 (possible for DEM-merged fault
    priors) makes setting that bit *cheaper* than leaving it clear, which a
    clamp-to-positive would silently invert.  Only the p->0/1 endpoints are
    clipped for finiteness."""
    p = np.clip(np.asarray(channel_probs, dtype=np.float64), 1e-12, 1 - 1e-7)
    return np.log((1 - p) / p)


def osd_decode_batch(
    h: np.ndarray,
    syndromes: np.ndarray,
    posterior_llrs: np.ndarray,
    channel_probs: np.ndarray,
    *,
    osd_method: str = "osd_e",
    osd_order: int = 10,
    nthreads: int = 0,
) -> np.ndarray:
    """OSD-decode a batch of syndromes. Returns (B, n) uint8 errors."""
    h = gf2.to_gf2(h)
    m, n = h.shape
    syndromes = np.ascontiguousarray(np.atleast_2d(syndromes).astype(np.uint8))
    b = syndromes.shape[0]
    if b == 0:
        return np.zeros((0, n), dtype=np.uint8)
    llrs = np.ascontiguousarray(
        np.broadcast_to(np.asarray(posterior_llrs, np.float64), (b, n))
    )
    cost = np.ascontiguousarray(_channel_cost(channel_probs))
    if cost.ndim == 0:
        cost = np.full(n, float(cost))
    method = _METHODS[osd_method]
    osd_order = _check_osd_order(osd_order)

    lib = load_native()
    if lib is not None:
        out = np.zeros((b, n), dtype=np.uint8)
        import ctypes

        u8p = ctypes.POINTER(ctypes.c_uint8)
        dp = ctypes.POINTER(ctypes.c_double)
        rc = lib.qldpc_osd_decode_batch(
            h.ctypes.data_as(u8p), m, n,
            syndromes.ctypes.data_as(u8p),
            llrs.ctypes.data_as(dp), b,
            cost.ctypes.data_as(dp), method, int(osd_order),
            int(nthreads), out.ctypes.data_as(u8p),
        )
        if rc == 0:
            return out
    return _osd_numpy(h, syndromes, llrs, cost, method, int(osd_order))


def _osd_numpy(h, syndromes, llrs, cost, method, osd_order):
    """Reference numpy implementation (fallback + test oracle for the C++)."""
    m, n = h.shape
    out = np.zeros((syndromes.shape[0], n), dtype=np.uint8)
    for bi in range(syndromes.shape[0]):
        order = np.argsort(llrs[bi], kind="stable")
        hp = h[:, order].copy()
        u = syndromes[bi].copy()
        # full RREF with syndrome carried
        pivots, free = [], []
        r = 0
        for col in range(n):
            if r >= m:
                free.append(col)
                continue
            sub = np.nonzero(hp[r:, col])[0]
            if sub.size == 0:
                free.append(col)
                continue
            piv = r + sub[0]
            if piv != r:
                hp[[r, piv]] = hp[[piv, r]]
                u[[r, piv]] = u[[piv, r]]
            rows = np.nonzero(hp[:, col])[0]
            for i in rows:
                if i != r:
                    hp[i] ^= hp[r]
                    u[i] ^= u[r]
            pivots.append(col)
            r += 1
        pivots = np.array(pivots, dtype=int)
        free = np.array(free, dtype=int)
        perm_cost = cost[order]

        def solve(t_bits):
            e_s = u[: len(pivots)].copy()
            for fj in t_bits:
                e_s ^= hp[: len(pivots), free[fj]]
            c = perm_cost[pivots] @ e_s + sum(perm_cost[free[fj]] for fj in t_bits)
            return e_s, c

        best_es, best_c = solve([])
        best_t: list[int] = []
        cands: list[list[int]] = []
        if method == 1:
            w = min(osd_order, len(free), OSD_CS_MAX_ORDER)
            for pat in range(1, 1 << w):
                cands.append([b for b in range(w) if (pat >> b) & 1])
        elif method == 2:
            cands.extend([[b] for b in range(len(free))])
            w = min(osd_order, len(free), OSD_CS_MAX_ORDER)
            cands.extend([[a, b] for a in range(w) for b in range(a + 1, w)])
        for t in cands:
            e_s, c = solve(t)
            if c < best_c:
                best_es, best_c, best_t = e_s, c, t
        e_perm = np.zeros(n, dtype=np.uint8)
        e_perm[pivots] = best_es
        for fj in best_t:
            e_perm[free[fj]] = 1
        out[bi, order] = e_perm
    return out


def osd_postprocess(
    h,
    syndromes,
    bp_errors,
    bp_converged,
    posterior_llrs,
    channel_probs,
    *,
    osd_method: str = "osd_e",
    osd_order: int = 10,
) -> np.ndarray:
    """Combine BP output with OSD on the non-converged shots (bposd semantics)."""
    from ..utils import telemetry
    from ..utils.observability import stage_timer

    bp_errors = np.asarray(bp_errors, dtype=np.uint8)
    conv = np.asarray(bp_converged, dtype=bool)
    if conv.all():
        return bp_errors
    idx = np.nonzero(~conv)[0]
    telemetry.count("osd.invocations")
    telemetry.count("osd.shots", int(idx.size))
    with stage_timer("osd_host"):
        fixed = osd_decode_batch(
            h,
            np.asarray(syndromes)[idx],
            np.asarray(posterior_llrs)[idx],
            channel_probs,
            osd_method=osd_method,
            osd_order=osd_order,
        )
    out = bp_errors.copy()
    out[idx] = fixed
    return out
