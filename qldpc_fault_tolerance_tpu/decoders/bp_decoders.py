"""Decoder objects and factory classes.

Mirrors the reference decoder surface (src/Decoders.py, src/Decoders_SpaceTime.py)
on top of the TPU BP kernel:

  * ``BPDecoder`` / ``BPOSD_Decoder`` / ``FirstMinBPDecoder`` — same constructor
    signatures and ``.decode(synd) -> correction`` / ``.h`` contract as the
    reference wrappers, but batched: every decoder also exposes
    ``decode_batch`` (host arrays in/out) and ``bp_batch_device`` for in-jit
    composition by the simulators.
  * ``DecoderClass`` factories — same ``GetDecoder(code_and_noise_channel_params)``
    params-dict contract (keys 'h', 'p_data', 'p_syndrome', 'num_rep',
    'code_h', 'channel_probs'; src/Decoders.py:94-97,107-120).
"""
from __future__ import annotations

import os
from abc import ABC, abstractmethod

import jax
import jax.numpy as jnp
import numpy as np

from ..codes import gf2
from ..ops import bp
from ..utils import profiling
from .osd import osd_postprocess

__all__ = [
    "device_syndrome_width",
    "kernel_variant",
    "osd_compaction_tiers",
    "BPDecoder",
    "BPOSD_Decoder",
    "FirstMinBPDecoder",
    "GetSpaceTimeCheckMat",
    "ST_BP_Decoder_syndrome",
    "ST_BP_Decoder_Circuit",
    "ST_BPOSD_Decoder_Circuit",
    "DecoderClass",
    "BP_Decoder_Class",
    "BPOSD_Decoder_Class",
    "FirstMinBP_Decoder_Class",
    "ST_BP_Decoder_Class",
    "ST_BP_Decoder_Circuit_Class",
    "ST_BPOSD_Decoder_Circuit_Class",
]

_BP_METHOD_ALIASES = {
    "minimum_sum": "minimum_sum",
    "min_sum": "minimum_sum",
    "ms": "minimum_sum",
    "msl": "minimum_sum",
    "product_sum": "product_sum",
    "ps": "product_sum",
    "psl": "product_sum",
}


def _norm_method(bp_method: str) -> str:
    return _BP_METHOD_ALIASES[str(bp_method).lower()]


def osd_compaction_tiers(batch_size: int) -> tuple:
    """Straggler-compaction capacities a ``bposd_dev`` decode of this batch
    size instantiates, ascending (empty for batches too small to compact).
    ONE definition shared by the dispatch logic in ``decode_device`` and
    the telemetry tier-occupancy accounting (utils.telemetry
    ``device_tele_vec``), so the occupancy counters can never drift from
    the program the decode actually runs."""
    B = int(batch_size)
    return tuple(c for c in dict.fromkeys((max(B // 16, 128),
                                           max(B // 4, 128)))
                 if c < B and c % 128 == 0)


def decode_device(static, state, syndromes):
    """Value-based device decode: the traced program depends only on
    ``static`` (a hashable tuple from ``decoder.device_static``) while every
    array — Tanner graph, channel LLRs — arrives through ``state`` (a pytree
    from ``decoder.device_state``).

    This is the key to compile sharing across a sweep: simulators jit their
    pipelines with the decoder *statics* in the cache key and the decoder
    *state* as traced arguments, so the 6 p-points of a threshold grid (or
    the codes of equal shape) reuse one executable instead of recompiling
    per (code, p) cell.  Semantically identical to
    ``decoder.decode_batch_device(syndromes)``.
    """
    kind = static[0]
    if kind == "bposd_dev":
        _, bp_static, n, rank, osd_order, elim = static[:6]
        # the 7th slot (ISSUE 19, additive) names the reprocessing
        # method; older 6-tuples mean the OSD-E scorer
        osd_method = static[6] if len(static) > 6 else "osd_e"
        err, aux = decode_device(bp_static, state, syndromes)
        if osd_method == "osd_cs":
            from ..ops.osd_cs_device import cs_pat_chunk
            from ..ops.osd_cs_device import \
                osd_cs_decode_values as osd_decode_values

            cfg = (n, rank, osd_order,
                   cs_pat_chunk(n, rank, osd_order), elim)
        else:
            from ..ops.osd_device import osd_decode_values

            cfg = (n, rank, osd_order, 256, elim)
        B = syndromes.shape[0]
        conv = aux["converged"]
        bad = ~conv
        if B < 64:
            def run_small(_):
                osd_err = osd_decode_values(
                    cfg, state["osd_packed"], state["osd_cost"],
                    syndromes, aux["posterior_llr"],
                )
                return jnp.where(conv[:, None], err, osd_err)

            # skip the elimination entirely when every shot converged (the
            # host path's conv.all() early return)
            out = jax.lax.cond(bad.any(), run_small, lambda _: err,
                               operand=None)
            return out, aux

        # straggler compaction (same trick as bp_decode_two_phase): OSD only
        # the BP-failed shots, gathered into a fixed-capacity sub-batch
        # (a small tier at B/16 and a mid tier at B/4, then full batch):
        # OSD cost is linear in the compacted size, so when most shots
        # converge the tier wins; results never depend on which tier runs.
        # Tiers stay multiples of 128 (the Pallas elimination's batch-tile
        # width).
        def compacted_fn(capacity):
            def run(_):
                idx = jnp.nonzero(bad, size=capacity, fill_value=B)[0]
                idx_c = jnp.minimum(idx, B - 1)
                sub = osd_decode_values(
                    cfg, state["osd_packed"], state["osd_cost"],
                    syndromes[idx_c], aux["posterior_llr"][idx_c],
                )
                # out-of-range pad indices are dropped by the scatter
                return err.at[idx].set(sub, mode="drop")

            return run

        def full(_):
            osd_err = osd_decode_values(
                cfg, state["osd_packed"], state["osd_cost"],
                syndromes, aux["posterior_llr"],
            )
            return jnp.where(conv[:, None], err, osd_err)

        def none(_):
            return err

        n_bad = bad.sum()
        # two tiers (B//16, B//4), floored at 128 (the Pallas batch-tile
        # width, so small batches still compact — the Pallas elimination
        # needs the multiple-of-128 capacity; non-conforming sizes route to
        # the XLA twin).  Each tier instantiates the full OSD program
        # (elimination + scoring) in the traced pipeline, so the ladder is
        # kept short; at flagship batch sizes the small tier covers the
        # common low-p case (a few stragglers) at 1/16th the elimination
        # cost.  Tier selection changes the program PATH only, never a
        # shot's result — pinned by the tier-equivalence test.
        tiers = list(osd_compaction_tiers(B))
        out = full
        for cap in reversed(tiers):
            out = (lambda cap, nxt: lambda o: jax.lax.cond(
                n_bad <= cap, compacted_fn(cap), nxt, o))(cap, out)
        out = jax.lax.cond(n_bad == 0, none, out, operand=None)
        return out, aux
    if kind == "st_syndrome":
        _, num_rep, m, n, inner = static
        b = syndromes.shape[0]
        synd = syndromes.reshape(b, num_rep * m)
        corr, aux = decode_device(inner, state, synd)
        data = corr.reshape(b, num_rep, n + m)[:, :, :n]
        folded = (jnp.sum(data.astype(jnp.int32), axis=1) % 2).astype(jnp.uint8)
        return folded, aux
    if kind == "firstmin":
        _, max_restarts, msf = static
        corr, w = bp.first_min_bp_decode(
            state["graph"], syndromes, state["llr0"],
            max_restarts=max_restarts, ms_scaling_factor=msf,
        )
        return corr, {"final_weight": w}
    assert kind == "bp", kind
    # head_tag routes the kernel variant: "none" (plain XLA), "v1" (dense
    # one-hot Pallas), "v2" (sparse index-gather), "v2_int8" (quantized
    # v2); the array side of the head rides in state["pallas"]
    _, max_iter, method, msf, two_phase, head_tag = static
    if (two_phase and syndromes.ndim == 2
            and syndromes.shape[0] >= bp.TWO_PHASE_MIN_BATCH
            and max_iter >= bp.TWO_PHASE_MIN_ITER):
        res = bp.bp_decode_two_phase(
            state["graph"], syndromes, state["llr0"],
            max_iter=max_iter, method=method, ms_scaling_factor=msf,
            pallas_head=state["pallas"],
            quantize="int8" if head_tag == "v2_int8" else None,
        )
    else:
        res = bp.bp_decode(
            state["graph"], syndromes, state["llr0"],
            max_iter=max_iter, method=method, ms_scaling_factor=msf,
        )
    return res.error, {
        "converged": res.converged, "posterior_llr": res.posterior_llr,
        "iterations": res.iterations,
    }


_decode_device_jit = jax.jit(decode_device, static_argnums=0)


def device_syndrome_width(static, state) -> int:
    """Columns of the syndrome batch a value-based decode program consumes —
    what the serving layer (serve/session.py) sizes its padded request
    buckets by.  Defined here because it knows the static layouts: the
    space-time wrapper flattens ``num_rep`` detector slices into one row;
    every other kind reads the check count off the Tanner graph in
    ``state`` (bposd_dev / firstmin states carry the same ``graph`` leaf)."""
    if static[0] == "st_syndrome":
        _, num_rep, m, _n, _inner = static
        return int(num_rep) * int(m)
    return int(state["graph"].chk_mask.shape[0])


def _maybe_pallas_head(bp_method: str, graph_host, quantize=None,
                       kernel: str | None = None):
    """Resolve the decoder's BP head: ``(head_object, head_tag)`` — the
    construction-time gate shared by ``BPDecoder.__init__`` and the factory
    classes' ``GetDecoderState`` fast path (one definition, so the two can
    never disagree about what program a decoder runs).

    ``kernel`` (default env ``QLDPC_BP_KERNEL``, "v2") selects the Pallas
    generation: "v2" = sparse index-gather incidence (ops/bp_pallas
    SparseHeadGraph — the only head honoring ``quantize``), "v1" = the
    dense one-hot stack, "xla" = no head.  Tags: "none"/"v1"/"v2"/
    "v2_int8" — the tag rides in ``device_static`` so the traced program
    (and every jit cache key) names its kernel.

    A ``quantize`` request builds the v2 head on ANY backend: off-TPU the
    head routes to the bit-exact XLA twin, so the int8 numerics (and their
    WER-parity contract) are testable on CPU."""
    if bp_method != "minimum_sum" or os.environ.get("QLDPC_PALLAS",
                                                    "1") == "0":
        if quantize:
            raise ValueError(
                "quantize='int8' needs the min-sum v2 head (QLDPC_PALLAS=0 "
                "or a non-min-sum method disables it)")
        return None, "none"
    kernel = kernel or os.environ.get("QLDPC_BP_KERNEL", "v2")
    if kernel not in ("v1", "v2", "xla"):
        raise ValueError(f"unknown QLDPC_BP_KERNEL {kernel!r}")
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        on_tpu = False
    from ..ops.bp_pallas import build_pallas_head, build_sparse_head

    if quantize:
        if kernel == "v1":
            raise ValueError("quantize='int8' requires the v2 kernel")
        from ..ops.bp_pallas import v2_mosaic_supported

        sg = build_sparse_head(graph_host)
        if not sg.fits_vmem():
            raise ValueError(
                f"quantize='int8' head infeasible for this shape "
                f"(fixed VMEM overhead {sg.fixed_overhead_bytes})")
        # fail FAST here rather than on every decode: int8 was explicitly
        # requested, so a toolchain whose mosaic lowering rejects the v2
        # kernel shape should surface at construction (off-TPU the probe
        # is trivially True — the twin serves)
        if not v2_mosaic_supported(quantize="int8"):
            raise ValueError(
                "quantize='int8' requested but this TPU toolchain fails "
                "the one-time v2/int8 mosaic probe "
                "(ops.bp_pallas.v2_mosaic_supported)")
        return sg, "v2_int8"
    if not on_tpu or kernel == "xla":
        return None, "none"
    if kernel == "v2":
        from ..ops.bp_pallas import v2_mosaic_supported

        sg = build_sparse_head(graph_host)
        if sg.fits_vmem() and v2_mosaic_supported():
            return sg, "v2"
        # v2's gate admits everything v1's does, but stay honest: fall
        # through to v1's own gate (or, when the one-time mosaic probe
        # failed, to the proven v1 kernel) rather than silently going XLA
    pg = build_pallas_head(graph_host)
    if pg.fits_vmem():
        return pg, "v1"
    return None, "none"


def _head_engages(static, state, batch_size: int) -> bool:
    """Whether a "bp" decode of ``batch_size`` shots actually enters the
    Pallas-head path (mirrors the gates in ``decode_device`` /
    ``bp.bp_decode_two_phase``): two-phase eligibility plus the per-batch
    tile gates.  Used by ``kernel_variant`` so a decode the head
    disengages from (sub-TWO_PHASE_MIN_BATCH, non-dividing bucket, no
    feasible tile) reports the f32 XLA path it really runs, not the
    kernel its head tag names."""
    _, max_iter, _method, _msf, two_phase, _tag = static
    if not (two_phase and batch_size >= bp.TWO_PHASE_MIN_BATCH
            and max_iter >= bp.TWO_PHASE_MIN_ITER):
        return False
    head = (state or {}).get("pallas")
    if head is None:
        return False
    pallas_block = 256  # bp_decode_two_phase's default
    return (batch_size % pallas_block == 0
            and head.max_block_b(batch_size, want=pallas_block) > 0)


def kernel_variant(static, state, batch_size: int | None = None) -> str:
    """Which BP kernel a value-based decode with this (static, state) pair
    actually routes to — one of ``ops.bp_pallas.KERNEL_VARIANTS``
    (dense_onehot / sparse_gather / sparse_int8 / xla_twin).  Resolves
    through the bposd/space-time wrappers; decoders without a BP stage
    (FirstMin) report "xla_twin".  With ``batch_size`` the per-batch
    engage gates apply too, so e.g. a quantized decoder serving a
    sub-``TWO_PHASE_MIN_BATCH`` request reports the exact-f32 "xla_twin"
    path it really runs.  This is what the engines publish as the
    ``bp.kernel_variant`` gauge and the ``wer_run`` event field, and what
    serve sessions record per compiled bucket — silent routing to the XLA
    twin is no longer traceless."""
    kind = static[0]
    if kind == "bposd_dev":
        return kernel_variant(static[1], state, batch_size)
    if kind == "st_syndrome":
        return kernel_variant(static[4], state, batch_size)
    if kind != "bp":
        return "xla_twin"
    head_tag = static[5]
    if head_tag in ("none", False, None):
        return "xla_twin"
    if batch_size is not None and not _head_engages(static, state,
                                                    batch_size):
        return "xla_twin"
    if head_tag in ("v2", "v2_int8"):
        from ..ops.bp_pallas import sparse_serves_pallas

        if not sparse_serves_pallas():
            return "xla_twin"
        return "sparse_int8" if head_tag == "v2_int8" else "sparse_gather"
    if head_tag == "v1" or head_tag is True:   # pre-v2 statics used a bool
        return "dense_onehot"
    return "xla_twin"


class FusedBPPair:
    """Two independent plain-BP decodes fused into one kernel call.

    Builds the block-diagonal Tanner graph of ``dec_a.h`` and ``dec_b.h`` and
    decodes both syndromes in one ``bp_decode_two_phase`` invocation with
    per-sector convergence/freeze (ops/bp.bp_decode ``sectors=``), so results
    are bit-identical to running the two decoders separately while paying the
    iteration-loop and straggler-tail costs once.  Used by the simulators to
    fuse their X-/Z-sector decodes (the reference runs two sequential native
    decoders per shot, src/Simulators.py:129-133).
    """

    @staticmethod
    def compatible(dec_a, dec_b) -> bool:
        return (
            type(dec_a) is BPDecoder and type(dec_b) is BPDecoder
            and dec_a.max_iter == dec_b.max_iter
            and dec_a.bp_method == dec_b.bp_method
            and dec_a.ms_scaling_factor == dec_b.ms_scaling_factor
            and dec_a.two_phase and dec_b.two_phase
        )

    def __init__(self, dec_a, dec_b):
        ha, hb = dec_a._h01, dec_b._h01
        (ma, na), (mb, nb) = ha.shape, hb.shape
        h = np.zeros((ma + mb, na + nb), dtype=np.uint8)
        h[:ma, :na] = ha
        h[ma:, na:] = hb
        self.graph = bp.build_tanner_graph(h)
        self.sectors = ((ma, mb), (na, nb))
        self._split = na
        self.llr0 = jnp.concatenate([dec_a.llr0, dec_b.llr0])
        self.max_iter = dec_a.max_iter
        self.bp_method = dec_a.bp_method
        self.ms_scaling_factor = dec_a.ms_scaling_factor

    def decode_pair_device(self, synd_a, synd_b):
        """(B, ma), (B, mb) -> corrections (B, na), (B, nb)."""
        synd = jnp.concatenate(
            [jnp.asarray(synd_a), jnp.asarray(synd_b)], axis=-1
        )
        res = bp.bp_decode_two_phase(
            self.graph,
            synd,
            self.llr0,
            max_iter=self.max_iter,
            method=self.bp_method,
            ms_scaling_factor=self.ms_scaling_factor,
            sectors=self.sectors,
        )
        return res.error[:, : self._split], res.error[:, self._split:]


class BPDecoder:
    """Plain BP decoder (reference BPDecoder, src/Decoders.py:77-90)."""

    def __init__(self, h, channel_probs, max_iter, bp_method="minimum_sum",
                 ms_scaling_factor=0.625, two_phase: bool = True,
                 quantize: str | None = None,
                 bp_kernel: str | None = None):
        self.h = np.asarray(h)
        self._h01 = gf2.to_gf2(h)
        self._graph_host = bp.build_tanner_graph_host(self._h01)
        self.graph = bp.build_tanner_graph(self._h01)
        self.channel_probs = np.broadcast_to(
            np.asarray(channel_probs, np.float64), (self._h01.shape[1],)
        ).copy()
        # the reference factories pass float max_iter (num_qubits/ratio,
        # src/Decoders.py:123) and let the native decoder coerce — match that
        self.max_iter = max(1, int(max_iter))
        self.bp_method = _norm_method(bp_method)
        self.ms_scaling_factor = float(ms_scaling_factor)
        # straggler compaction (ops/bp.bp_decode_two_phase): bit-identical
        # results, ~max_iter/head_iters less HBM traffic at low p
        self.two_phase = bool(two_phase)
        if quantize not in (None, "int8"):
            raise ValueError(f"unknown quantize mode {quantize!r}")
        # int8 min-sum messages on the v2 head (ops/bp_pallas): NOT
        # bit-exact with the f32/bf16 decoders — statistical WER parity
        # within the documented tolerance (README "BP kernel v2")
        self.quantize = quantize
        self.llr0 = bp.llr_from_probs(self.channel_probs)
        # VMEM-resident Pallas head (ops/bp_pallas): ~10x head throughput on
        # TPU; stragglers still go through the exact f32 XLA tail.  Gated on
        # backend, method, and the incidence data fitting VMEM.  v2 (sparse
        # index-gather incidence) is the default; ``bp_kernel`` (or env
        # QLDPC_BP_KERNEL) = "v1"|"xla" selects the dense one-hot stack /
        # plain XLA for A/B work (bench.py kernel arms).
        self._pallas_head, self._head_tag = _maybe_pallas_head(
            self.bp_method, self._graph_host, quantize=self.quantize,
            kernel=bp_kernel)
        # surface calibration gates the table marks unmeasured — one-shot
        # telemetry, not a warning per decoder
        profiling.note_unmeasured_gates()

    needs_host_postprocess = False

    # --- value-based device interface (compile sharing across sweeps) ---
    @property
    def device_static(self):
        """Hashable program config — goes into the jit cache key."""
        return ("bp", self.max_iter, self.bp_method,
                float(self.ms_scaling_factor), self.two_phase,
                self._head_tag)

    @property
    def kernel_variant(self) -> str:
        """Which BP kernel this decoder's decodes route to (one of
        ``ops.bp_pallas.KERNEL_VARIANTS``)."""
        return kernel_variant(self.device_static, self.device_state)

    @property
    def device_state(self):
        """Pytree of arrays — traced arguments, value changes don't retrace."""
        return {"graph": self.graph, "llr0": self.llr0,
                "pallas": self._pallas_head}

    # --- device-side (for composition inside jitted simulators) ---
    def decode_batch_device(self, syndromes):
        """Uniform device interface: returns (corrections (B,n) uint8, aux dict)."""
        res = self.bp_batch_device(syndromes)
        return res.error, {"converged": res.converged,
                           "posterior_llr": res.posterior_llr,
                           "iterations": res.iterations}

    def host_postprocess(self, syndromes, corrections, aux):
        """No-op for plain BP (bposd applies OSD only on BP failure)."""
        return corrections

    def bp_batch_device(self, syndromes) -> bp.BPResult:
        if self.two_phase and syndromes.ndim == 2 \
                and syndromes.shape[0] >= bp.TWO_PHASE_MIN_BATCH \
                and self.max_iter >= bp.TWO_PHASE_MIN_ITER:
            return bp.bp_decode_two_phase(
                self.graph,
                syndromes,
                self.llr0,
                max_iter=self.max_iter,
                method=self.bp_method,
                ms_scaling_factor=self.ms_scaling_factor,
                pallas_head=self._pallas_head,
                quantize=self.quantize,
            )
        return bp.bp_decode(
            self.graph,
            syndromes,
            self.llr0,
            max_iter=self.max_iter,
            method=self.bp_method,
            ms_scaling_factor=self.ms_scaling_factor,
        )

    # --- host-side batch API ---
    def decode_batch(self, syndromes) -> np.ndarray:
        from ..utils import telemetry

        res = self.bp_batch_device(jnp.asarray(np.atleast_2d(syndromes)))
        if telemetry.enabled():
            telemetry.record_bp_aux(
                {"converged": np.asarray(res.converged),
                 "iterations": np.asarray(res.iterations)})
        return np.asarray(res.error)

    def decode(self, synd):
        """Reference-compatible single-shot decode."""
        return self.decode_batch(np.atleast_2d(synd))[0]


class BPOSD_Decoder(BPDecoder):
    """BP + OSD (reference BPOSD_Decoder, src/Decoders.py:26-41).

    BP runs batched on device for the whole batch, and OSD post-processing
    is **device-resident by default on every substrate** (ops/osd_device.py:
    batched bit-packed GF(2) elimination — the blocked Pallas kernel on
    TPU, its bit-exact XLA twin elsewhere — plus MXU-scored OSD-E
    reprocessing; ops/osd_cs_device.py: the chunked order-w combination
    sweep for ``osd_method="osd_cs"``).  That keeps BPOSD pipelines pure
    device code (mesh-shardable, scan-chunkable, servable,
    megabatch-foldable with ``osd.host_round_trips == 0``).

    The host path (native C++ / numpy, _native/osd.cpp) is demoted to a
    resilience-ladder rung and test oracle for every method: ``decode_batch``
    falls back to it when the device OSD program faults and
    ``device_osd=False`` selects it explicitly.

    ``device_osd``: True / False / "auto" (device wherever the method is
    device-implementable; ``QLDPC_DEVICE_OSD=0`` restores the host
    default).  Both paths implement identical semantics (pinned against
    the same numpy oracle; costs are float32 on device vs the C++
    float64, so only float-tied candidates may differ).
    """

    def __init__(self, h, channel_probs, max_iter, bp_method="minimum_sum",
                 ms_scaling_factor=0.625, osd_method="osd_e", osd_order=10,
                 device_osd="auto"):
        super().__init__(h, channel_probs, max_iter, bp_method, ms_scaling_factor)
        self.osd_method = str(osd_method)
        from .osd import _METHODS, _check_osd_order

        self.osd_order = (_check_osd_order(osd_order)
                          if self.osd_method in _METHODS else int(osd_order))
        _DEVICE_METHODS = ("osd_e", "osd0", "osd_0", "exhaustive", "osd_cs")
        if device_osd == "auto":
            env = os.environ.get("QLDPC_DEVICE_OSD", "1")
            device_osd = (env != "0"
                          and self.osd_method in _DEVICE_METHODS)
        elif device_osd and self.osd_method not in _DEVICE_METHODS:
            raise NotImplementedError(
                f"device OSD implements OSD-0/OSD-E/OSD-CS only, not "
                f"{self.osd_method!r}; use device_osd=False"
            )
        self.device_osd = bool(device_osd)
        self._osd_plan = None
        if self.device_osd:
            from ..ops.osd_device import build_osd_plan

            self._osd_plan = build_osd_plan(self._h01, self.channel_probs)

    @property
    def needs_host_postprocess(self):
        return not self.device_osd

    @property
    def device_static(self):
        bp_static = super().device_static
        if not self.device_osd:
            return bp_static
        order = 0 if self.osd_method in ("osd0", "osd_0") else self.osd_order
        # the elimination strategy is resolved HERE (construction-time env)
        # and travels in the static config, so it participates in every jit
        # cache key — a mid-process env change affects new decoders only
        elim = os.environ.get("QLDPC_OSD_ELIM", "pallas")
        # slot 7 (additive, ISSUE 19): which reprocessing program runs —
        # "osd_cs" routes decode_device to the combination-sweep scorer
        method = "osd_cs" if self.osd_method == "osd_cs" else "osd_e"
        return ("bposd_dev", bp_static, self._osd_plan.n,
                self._osd_plan.rank, order, elim, method)

    @property
    def device_state(self):
        state = dict(super().device_state)
        if self.device_osd:
            state["osd_packed"] = self._osd_plan.packed
            state["osd_cost"] = self._osd_plan.cost
        return state

    def decode_batch_device(self, syndromes):
        if not self.device_osd:
            return super().decode_batch_device(syndromes)
        # jitted entry: called eagerly this wraps the whole dispatch in one
        # program (an eager lax.cond would re-trace its branches per call);
        # called inside a simulator's trace it simply inlines
        return _decode_device_jit(self.device_static, self.device_state,
                                  syndromes)

    def host_postprocess(self, syndromes, corrections, aux):
        from ..utils import telemetry

        if telemetry.enabled():
            # the aux is already host-bound on this path: BP stats (and one
            # counted host round-trip) come for free here
            telemetry.record_bp_aux(aux)
            telemetry.count("osd.host_round_trips")
        return self.osd_host(
            np.asarray(syndromes),
            np.asarray(corrections),
            np.asarray(aux["converged"]),
            np.asarray(aux["posterior_llr"]),
        )

    def decode_batch(self, syndromes) -> np.ndarray:
        from ..utils import telemetry

        syndromes = np.atleast_2d(np.asarray(syndromes))
        if self.device_osd:
            try:
                out, aux = self.decode_batch_device(jnp.asarray(syndromes))
                # materialize INSIDE the try: device dispatches are async,
                # so an execution-time worker fault surfaces at the fetch —
                # the fallback must cover it, not just trace/compile errors
                out = np.asarray(out)
                aux = {k: np.asarray(v) for k, v in aux.items()
                       if k in ("converged", "iterations")}
            except Exception:
                # resilience rung: the demoted host C++/numpy path serves
                # the batch when the device OSD program faults (compile,
                # dispatch, or execution) — same semantics, pinned against
                # the same oracle, so the fallback is loud in telemetry
                # but silent in results
                telemetry.count("osd.host_fallbacks")
                telemetry.event("degrade", rung="device_osd->host")
                res = self.bp_batch_device(jnp.asarray(syndromes))
                if telemetry.enabled():
                    telemetry.record_bp_aux(
                        {"converged": np.asarray(res.converged),
                         "iterations": np.asarray(res.iterations)})
                return self.osd_host(
                    syndromes, np.asarray(res.error),
                    np.asarray(res.converged),
                    np.asarray(res.posterior_llr))
            if telemetry.enabled():
                telemetry.record_bp_aux(aux)
                conv = aux.get("converged")
                if conv is not None:
                    # mirror device_tele_vec: BP-failed shots routed to the
                    # device OSD stage count as OSD fallback pressure
                    telemetry.count("osd.device_shots",
                                    int((~conv).sum()))
            return out
        res = self.bp_batch_device(jnp.asarray(syndromes))
        if telemetry.enabled():
            telemetry.record_bp_aux(
                {"converged": np.asarray(res.converged),
                 "iterations": np.asarray(res.iterations)})
        return self.osd_host(
            syndromes, np.asarray(res.error), np.asarray(res.converged),
            np.asarray(res.posterior_llr),
        )

    def osd_host(self, syndromes, bp_errors, converged, posterior_llrs) -> np.ndarray:
        return osd_postprocess(
            self._h01, syndromes, bp_errors, converged, posterior_llrs,
            self.channel_probs, osd_method=self.osd_method, osd_order=self.osd_order,
        )


class FirstMinBPDecoder:
    """Sequential-restart decoder (reference FirstMinBPDecoder, src/Decoders.py:49-74)."""

    def __init__(self, h, channel_probs, max_iter, bp_method="minimum_sum",
                 ms_scaling_factor=0.9):
        if _norm_method(bp_method) != "minimum_sum":
            raise NotImplementedError("FirstMinBPDecoder supports min-sum only")
        self.h = np.asarray(h)
        self._h01 = gf2.to_gf2(h)
        self.graph = bp.build_tanner_graph(self._h01)
        self.channel_probs = np.broadcast_to(
            np.asarray(channel_probs, np.float64), (self._h01.shape[1],)
        ).copy()
        self.max_iter = max(1, int(max_iter))
        self.ms_scaling_factor = float(ms_scaling_factor)
        self.llr0 = bp.llr_from_probs(self.channel_probs)

    needs_host_postprocess = False

    @property
    def device_static(self):
        return ("firstmin", self.max_iter, float(self.ms_scaling_factor))

    @property
    def device_state(self):
        return {"graph": self.graph, "llr0": self.llr0}

    def decode_batch_device(self, syndromes):
        corr, w = bp.first_min_bp_decode(
            self.graph,
            syndromes,
            self.llr0,
            max_restarts=self.max_iter,
            ms_scaling_factor=self.ms_scaling_factor,
        )
        return corr, {"final_weight": w}

    def host_postprocess(self, syndromes, corrections, aux):
        return corrections

    def decode_batch(self, syndromes) -> np.ndarray:
        corr, _ = bp.first_min_bp_decode(
            self.graph,
            jnp.asarray(np.atleast_2d(syndromes)),
            self.llr0,
            max_restarts=self.max_iter,
            ms_scaling_factor=self.ms_scaling_factor,
        )
        return np.asarray(corr)

    def decode(self, synd):
        return self.decode_batch(np.atleast_2d(synd))[0]


def GetSpaceTimeCheckMat(h, t0: int) -> np.ndarray:
    """Block-lower-bidiagonal space-time check matrix (src/Decoders.py:179-194).

    Diagonal blocks [H | I_m]; first subdiagonal blocks [0 | I_m]; t0*m rows by
    t0*(n+m) columns.
    """
    h = gf2.to_gf2(h)
    m, n = h.shape
    eye = np.eye(m, dtype=np.uint8)
    zero = np.zeros_like(h)
    st = np.zeros((t0 * m, t0 * (n + m)), dtype=np.uint8)
    for i in range(t0):
        st[i * m:(i + 1) * m, i * (n + m):i * (n + m) + n] = h
        st[i * m:(i + 1) * m, i * (n + m) + n:(i + 1) * (n + m)] = eye
        if i >= 1:
            j = i - 1
            st[i * m:(i + 1) * m, j * (n + m):j * (n + m) + n] = zero
            st[i * m:(i + 1) * m, j * (n + m) + n:(j + 1) * (n + m)] = eye
    return st


class ST_BP_Decoder_syndrome:
    """Space-time syndrome decoder (src/Decoders.py:200-223): BP over the
    block-bidiagonal matrix; output is the XOR of the per-slice data-error
    estimates."""

    def __init__(self, h, p_data, p_synd, max_iter, bp_method="minimum_sum",
                 ms_scaling_factor=0.625, num_rep=1):
        h = gf2.to_gf2(h)
        self.num_checks, self.num_qubits = h.shape
        self.h = h
        self.num_rep = int(num_rep)
        self.ST_h = GetSpaceTimeCheckMat(h, self.num_rep)
        probs = np.concatenate(
            [np.full(self.num_qubits, p_data), np.full(self.num_checks, p_synd)]
        )
        self._bp = BPDecoder(
            self.ST_h,
            np.tile(probs, self.num_rep),
            max_iter,
            bp_method,
            ms_scaling_factor,
        )

    needs_host_postprocess = False

    @property
    def device_static(self):
        return ("st_syndrome", self.num_rep, self.num_checks,
                self.num_qubits, self._bp.device_static)

    @property
    def device_state(self):
        return self._bp.device_state

    def decode_batch_device(self, detector_histories):
        """Device path: (B, num_rep, m) detector histories -> (B, n) folded
        data corrections (XOR of per-slice data-error estimates,
        src/Decoders.py:215-223)."""
        arr = detector_histories
        b = arr.shape[0]
        synd = arr.reshape(b, self.num_rep * self.num_checks)
        corr, aux = self._bp.decode_batch_device(synd)
        blk = self.num_qubits + self.num_checks
        data = corr.reshape(b, self.num_rep, blk)[:, :, : self.num_qubits]
        folded = (jnp.sum(data.astype(jnp.int32), axis=1) % 2).astype(jnp.uint8)
        return folded, aux

    def host_postprocess(self, syndromes, corrections, aux):
        return corrections

    def decode_batch(self, detector_histories) -> np.ndarray:
        """detector_histories: (B, num_rep, m) -> (B, n) folded data corrections."""
        arr = np.asarray(detector_histories)
        if arr.ndim == 2:
            arr = arr[None]
        folded, _ = self.decode_batch_device(jnp.asarray(arr))
        return np.asarray(folded)

    def decode(self, detector_history):
        return self.decode_batch(np.asarray(detector_history)[None])[0]


class ST_BP_Decoder_Circuit(BPDecoder):
    """BP over a DEM-derived fault matrix (src/Decoders_SpaceTime.py:261-274)."""

    def __init__(self, h, channel_probs, max_iter, bp_method="minimum_sum",
                 ms_scaling_factor=0.625):
        super().__init__(h, channel_probs, max_iter, bp_method, ms_scaling_factor)


class ST_BPOSD_Decoder_Circuit(BPOSD_Decoder):
    """BP+OSD over a DEM-derived fault matrix (src/Decoders_SpaceTime.py:277-292)."""


# ---------------------------------------------------------------------------
# Factory classes: the GetDecoder(params-dict) plugin boundary
# ---------------------------------------------------------------------------

class DecoderClass(ABC):
    """Abstract factory (reference src/Decoders.py:94-97)."""

    @abstractmethod
    def GetDecoder(self, code_and_noise_channel_params):
        ...

    def GetDecoderState(self, code_and_noise_channel_params):
        """``(device_static, device_state)`` of the decoder ``GetDecoder``
        would build for these params — the per-cell payload the FUSED sweep
        planner (sweep/fused.py) stacks along the cell axis.

        The default constructs the decoder and reads both off it (always
        correct, pays the full per-cell build); library classes whose
        statics don't depend on the noise values override this to return
        the p-dependent state (LLR priors) without the rebuild."""
        dec = self.GetDecoder(code_and_noise_channel_params)
        return dec.device_static, dec.device_state


def _channel_from_params(params) -> tuple[np.ndarray, int]:
    """Shared channel-probs logic of the factories (src/Decoders.py:113-120):
    with 'p_syndrome' present, h is the extended [H|I] matrix and the channel
    is [p_data x n, p_syndrome x m]; otherwise uniform p_data."""
    h = np.asarray(params["h"])
    if "p_syndrome" in params:
        num_checks = h.shape[0]
        num_qubits = h.shape[1] - h.shape[0]
        probs = np.concatenate(
            [np.full(num_qubits, params["p_data"]),
             np.full(num_checks, params["p_syndrome"])]
        )
    else:
        num_qubits = h.shape[1]
        probs = np.full(num_qubits, params["p_data"])
    return probs, num_qubits


class BPOSD_Decoder_Class(DecoderClass):
    """src/Decoders.py:100-138."""

    def __init__(self, max_iter_ratio, bp_method, ms_scaling_factor, osd_method,
                 osd_order):
        self.decoder_default_params = {
            "max_iter_ratio": max_iter_ratio, "bp_method": bp_method,
            "ms_scaling_factor": ms_scaling_factor, "osd_method": osd_method,
            "osd_order": osd_order,
        }

    def GetDecoder(self, code_and_noise_channel_params):
        assert "h" in code_and_noise_channel_params, "missing the check matrix h"
        assert "p_data" in code_and_noise_channel_params, "missing the data error prob: p_data"
        probs, num_qubits = _channel_from_params(code_and_noise_channel_params)
        d = self.decoder_default_params
        return BPOSD_Decoder(
            h=code_and_noise_channel_params["h"],
            channel_probs=probs,
            max_iter=num_qubits / d["max_iter_ratio"],
            bp_method=d["bp_method"],
            ms_scaling_factor=d["ms_scaling_factor"],
            osd_method=d["osd_method"],
            osd_order=d["osd_order"],
        )


class BP_Decoder_Class(DecoderClass):
    """src/Decoders.py:141-172.  ``quantize`` (extra, default None) builds
    int8-min-sum decoders — the BENCH_QUANT A/B arm and int8 serve
    sessions come through here."""

    def __init__(self, max_iter_ratio, bp_method, ms_scaling_factor,
                 quantize: str | None = None):
        self.decoder_default_params = {
            "max_iter_ratio": max_iter_ratio, "bp_method": bp_method,
            "ms_scaling_factor": ms_scaling_factor, "quantize": quantize,
        }

    def GetDecoder(self, code_and_noise_channel_params):
        assert "h" in code_and_noise_channel_params, "missing the check matrix h"
        assert "p_data" in code_and_noise_channel_params, "missing the data error prob: p_data"
        probs, num_qubits = _channel_from_params(code_and_noise_channel_params)
        d = self.decoder_default_params
        return BPDecoder(
            h=code_and_noise_channel_params["h"],
            channel_probs=probs,
            max_iter=num_qubits / d["max_iter_ratio"],
            bp_method=d["bp_method"],
            ms_scaling_factor=d["ms_scaling_factor"],
            quantize=d.get("quantize"),
        )

    def GetDecoderState(self, code_and_noise_channel_params):
        """Fast path for the fused sweep planner: the (static, state) pair
        ``GetDecoder(params).device_static/device_state`` would expose,
        without building the decoder — the Tanner graph and Pallas head
        come from the per-H memo (ops/bp), so a sweep's non-representative
        cells cost one ``llr_from_probs``.  Pinned equal to the full build
        by tests/test_fused_sweep.py."""
        p = code_and_noise_channel_params
        assert "h" in p and "p_data" in p
        probs, num_qubits = _channel_from_params(p)
        d = self.decoder_default_params
        h01 = gf2.to_gf2(p["h"])
        graph_host = bp.build_tanner_graph_host(h01)
        graph = bp.build_tanner_graph(h01)
        method = _norm_method(d["bp_method"])
        pallas, head_tag = _maybe_pallas_head(method, graph_host,
                                              quantize=d.get("quantize"))
        static = ("bp", max(1, int(num_qubits / d["max_iter_ratio"])),
                  method, float(d["ms_scaling_factor"]), True, head_tag)
        channel = np.broadcast_to(
            np.asarray(probs, np.float64), (h01.shape[1],)).copy()
        state = {"graph": graph, "llr0": bp.llr_from_probs(channel),
                 "pallas": pallas}
        return static, state


class FirstMinBP_Decoder_Class(DecoderClass):
    """Factory for the restart decoder (used directly in the Single-Shot notebook)."""

    def __init__(self, max_iter_ratio, bp_method, ms_scaling_factor):
        self.decoder_default_params = {
            "max_iter_ratio": max_iter_ratio, "bp_method": bp_method,
            "ms_scaling_factor": ms_scaling_factor,
        }

    def GetDecoder(self, code_and_noise_channel_params):
        probs, num_qubits = _channel_from_params(code_and_noise_channel_params)
        d = self.decoder_default_params
        return FirstMinBPDecoder(
            h=code_and_noise_channel_params["h"],
            channel_probs=probs,
            max_iter=num_qubits / d["max_iter_ratio"],
            bp_method=d["bp_method"],
            ms_scaling_factor=d["ms_scaling_factor"],
        )


class ST_BP_Decoder_Class(DecoderClass):
    """src/Decoders.py:227-257.  Note the preserved reference quirk: when
    'p_syndrome' is present the syndrome prior is taken from p_data, not from
    the p_syndrome value (src/Decoders.py:243-246)."""

    def __init__(self, max_iter_ratio, bp_method, ms_scaling_factor):
        self.decoder_default_params = {
            "max_iter_ratio": max_iter_ratio, "bp_method": bp_method,
            "ms_scaling_factor": ms_scaling_factor,
        }

    def GetDecoder(self, code_and_noise_channel_params):
        p = code_and_noise_channel_params
        assert "h" in p and "p_data" in p and "num_rep" in p
        h = np.asarray(p["h"])
        p_data = p["p_data"]
        p_synd = p["p_data"] if "p_syndrome" in p else 0
        num_qubits = h.shape[1]
        d = self.decoder_default_params
        return ST_BP_Decoder_syndrome(
            h=h, p_data=p_data, p_synd=p_synd,
            max_iter=num_qubits / d["max_iter_ratio"],
            bp_method=d["bp_method"],
            ms_scaling_factor=d["ms_scaling_factor"],
            num_rep=p["num_rep"],
        )


class ST_BP_Decoder_Circuit_Class(DecoderClass):
    """src/Decoders_SpaceTime.py:296-321: max_iter scales with the *code* width
    (code_h), not the fault-matrix width."""

    def __init__(self, max_iter_ratio, bp_method, ms_scaling_factor):
        self.decoder_default_params = {
            "max_iter_ratio": max_iter_ratio, "bp_method": bp_method,
            "ms_scaling_factor": ms_scaling_factor,
        }

    def GetDecoder(self, code_and_noise_channel_params):
        p = code_and_noise_channel_params
        assert "h" in p and "code_h" in p and "channel_probs" in p
        num_qubits = np.asarray(p["code_h"]).shape[1]
        d = self.decoder_default_params
        return ST_BP_Decoder_Circuit(
            h=p["h"], channel_probs=p["channel_probs"],
            max_iter=int(num_qubits / d["max_iter_ratio"]),
            bp_method=d["bp_method"], ms_scaling_factor=d["ms_scaling_factor"],
        )


class ST_BPOSD_Decoder_Circuit_Class(DecoderClass):
    """src/Decoders_SpaceTime.py:323-357."""

    def __init__(self, max_iter_ratio, bp_method, ms_scaling_factor, osd_method,
                 osd_order):
        self.decoder_default_params = {
            "max_iter_ratio": max_iter_ratio, "bp_method": bp_method,
            "ms_scaling_factor": ms_scaling_factor, "osd_method": osd_method,
            "osd_order": osd_order,
        }

    def GetDecoder(self, code_and_noise_channel_params):
        p = code_and_noise_channel_params
        assert "h" in p and "code_h" in p and "channel_probs" in p
        num_qubits = np.asarray(p["code_h"]).shape[1]
        d = self.decoder_default_params
        return ST_BPOSD_Decoder_Circuit(
            h=p["h"], channel_probs=p["channel_probs"],
            max_iter=num_qubits / d["max_iter_ratio"],
            bp_method=d["bp_method"], ms_scaling_factor=d["ms_scaling_factor"],
            osd_method=d["osd_method"], osd_order=d["osd_order"],
        )
