from .samplers import (
    bit_flips,
    bit_flips_packed,
    bit_flips_tilted,
    bit_flips_tilted_packed,
    depolarizing_xz,
    depolarizing_xz_packed,
    depolarizing_xz_stratum,
    depolarizing_xz_tilted,
    depolarizing_xz_tilted_packed,
    fixed_weight_flips,
    stratum_log_weight,
)

__all__ = [
    "bit_flips",
    "bit_flips_packed",
    "bit_flips_tilted",
    "bit_flips_tilted_packed",
    "depolarizing_xz",
    "depolarizing_xz_packed",
    "depolarizing_xz_stratum",
    "depolarizing_xz_tilted",
    "depolarizing_xz_tilted_packed",
    "fixed_weight_flips",
    "stratum_log_weight",
]
