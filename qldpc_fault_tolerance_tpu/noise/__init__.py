from .samplers import (
    bit_flips,
    bit_flips_packed,
    depolarizing_xz,
    depolarizing_xz_packed,
)

__all__ = [
    "bit_flips",
    "bit_flips_packed",
    "depolarizing_xz",
    "depolarizing_xz_packed",
]
