from .samplers import bit_flips, depolarizing_xz

__all__ = ["bit_flips", "depolarizing_xz"]
