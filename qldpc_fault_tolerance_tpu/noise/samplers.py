"""PRNG-keyed noise samplers (pure JAX, vmappable).

Replaces the reference's per-qubit Python ``random.random()`` loops
(src/Simulators.py:89-115, 215-255).  Keyed sampling fixes the reference's
fork-RNG hazard (identical Mersenne-Twister streams in forked workers,
src/Simulators.py:101 + SURVEY §2.3): every shot derives an independent
stream from a fold-in of the shot index.

Convention: ``pauli_error_probs = [px, py, pz]`` with the reference's binning
order — u < pz -> Z; pz <= u < pz+px -> X; pz+px <= u < pz+px+py -> Y
(src/Simulators.py:102-113).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["depolarizing_xz", "bit_flips",
           "depolarizing_xz_packed", "bit_flips_packed"]


def depolarizing_xz(key, shape, pauli_error_probs):
    """Sample X/Z error components for independent single-qubit Pauli noise.

    shape: output shape, e.g. (batch, n).  Returns (error_x, error_z) uint8.
    """
    px, py, pz = (jnp.asarray(p, jnp.float32) for p in pauli_error_probs)
    u = jax.random.uniform(key, shape, dtype=jnp.float32)
    is_z = u < pz
    is_x = (u >= pz) & (u < pz + px)
    is_y = (u >= pz + px) & (u < pz + px + py)
    error_x = (is_x | is_y).astype(jnp.uint8)
    error_z = (is_z | is_y).astype(jnp.uint8)
    return error_x, error_z


def bit_flips(key, shape, p):
    """i.i.d. Bernoulli(p) flips (syndrome-measurement errors etc.)."""
    u = jax.random.uniform(key, shape, dtype=jnp.float32)
    return (u < jnp.asarray(p, jnp.float32)).astype(jnp.uint8)


def depolarizing_xz_packed(key, shape, pauli_error_probs):
    """Bit-packed ``depolarizing_xz``: same uniform draws for the same
    key/shape (bit-exact, shot for shot), returned as (ceil(B/32), n) uint32
    lane words.  Inside jit the uint8 planes fuse away — the sampler's only
    HBM write is the packed planes (8x fewer bytes).
    """
    from ..ops.gf2_packed import pack_shots

    error_x, error_z = depolarizing_xz(key, shape, pauli_error_probs)
    return pack_shots(error_x), pack_shots(error_z)


def bit_flips_packed(key, shape, p):
    """Bit-packed ``bit_flips`` (same draws, packed lane words)."""
    from ..ops.gf2_packed import pack_shots

    return pack_shots(bit_flips(key, shape, p))
