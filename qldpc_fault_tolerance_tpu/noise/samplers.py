"""PRNG-keyed noise samplers (pure JAX, vmappable).

Replaces the reference's per-qubit Python ``random.random()`` loops
(src/Simulators.py:89-115, 215-255).  Keyed sampling fixes the reference's
fork-RNG hazard (identical Mersenne-Twister streams in forked workers,
src/Simulators.py:101 + SURVEY §2.3): every shot derives an independent
stream from a fold-in of the shot index.

Convention: ``pauli_error_probs = [px, py, pz]`` with the reference's binning
order — u < pz -> Z; pz <= u < pz+px -> X; pz+px <= u < pz+px+py -> Y
(src/Simulators.py:102-113).

Weighted (importance-sampled) samplers for the rare-event subsystem
(``qldpc_fault_tolerance_tpu.rare``): the ``*_tilted`` variants draw from a
TILTED channel (tilt probabilities ``q`` larger than the physical ``p``) and
return a per-shot log importance weight ``log dP_p/dP_q`` alongside the error
planes.  They consume the SAME uniform draws as the direct samplers with the
tilt probabilities in the thresholds, so the zero-tilt configuration
(``tilt == p``) reproduces the direct samplers' error planes bit for bit with
an exactly-zero log weight — the contract the engines' zero-tilt bit-exactness
tests pin.  The ``*_stratum`` samplers draw fixed-Hamming-weight error
patterns uniformly within a stratum (the subset-splitting substrate); their
importance weight is CONSTANT per stratum and returned as the per-shot
log-weight plane for uniformity.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["depolarizing_xz", "bit_flips",
           "depolarizing_xz_packed", "bit_flips_packed",
           "depolarizing_xz_tilted", "bit_flips_tilted",
           "depolarizing_xz_tilted_packed", "bit_flips_tilted_packed",
           "fixed_weight_flips", "depolarizing_xz_stratum",
           "stratum_log_weight"]


def depolarizing_xz(key, shape, pauli_error_probs):
    """Sample X/Z error components for independent single-qubit Pauli noise.

    shape: output shape, e.g. (batch, n).  Returns (error_x, error_z) uint8.
    """
    px, py, pz = (jnp.asarray(p, jnp.float32) for p in pauli_error_probs)
    u = jax.random.uniform(key, shape, dtype=jnp.float32)
    is_z = u < pz
    is_x = (u >= pz) & (u < pz + px)
    is_y = (u >= pz + px) & (u < pz + px + py)
    error_x = (is_x | is_y).astype(jnp.uint8)
    error_z = (is_z | is_y).astype(jnp.uint8)
    return error_x, error_z


def bit_flips(key, shape, p):
    """i.i.d. Bernoulli(p) flips (syndrome-measurement errors etc.)."""
    u = jax.random.uniform(key, shape, dtype=jnp.float32)
    return (u < jnp.asarray(p, jnp.float32)).astype(jnp.uint8)


def depolarizing_xz_packed(key, shape, pauli_error_probs):
    """Bit-packed ``depolarizing_xz``: same uniform draws for the same
    key/shape (bit-exact, shot for shot), returned as (ceil(B/32), n) uint32
    lane words.  Inside jit the uint8 planes fuse away — the sampler's only
    HBM write is the packed planes (8x fewer bytes).
    """
    from ..ops.gf2_packed import pack_shots

    error_x, error_z = depolarizing_xz(key, shape, pauli_error_probs)
    return pack_shots(error_x), pack_shots(error_z)


def bit_flips_packed(key, shape, p):
    """Bit-packed ``bit_flips`` (same draws, packed lane words)."""
    from ..ops.gf2_packed import pack_shots

    return pack_shots(bit_flips(key, shape, p))


# ---------------------------------------------------------------------------
# Importance-sampled (tilted) channels
# ---------------------------------------------------------------------------
def _shot_sum(per_site):
    """Per-shot reduction of a (batch, ...) per-site plane -> (batch,)."""
    return per_site.reshape(per_site.shape[0], -1).sum(axis=-1)


def depolarizing_xz_tilted(key, shape, pauli_error_probs, tilt_probs):
    """Depolarizing sample from the TILTED channel ``tilt_probs`` with the
    per-shot log importance weight toward the target ``pauli_error_probs``.

    Returns ``(error_x, error_z, log_weight)`` with ``log_weight`` float32
    ``(batch,)``: sum over sites of ``log P_p(outcome) - log P_q(outcome)``.
    The uniform draw, binning order and dtype discipline match
    ``depolarizing_xz`` exactly, so ``tilt_probs == pauli_error_probs``
    yields bit-identical error planes and an exactly-zero log weight (every
    per-outcome term is ``log(p) - log(q)`` with ``p == q``).  A target
    component that is zero while its tilt is positive weights those shots
    to exactly zero via ``-inf`` log terms — the mathematically correct
    limit for an outcome the physical channel cannot produce.
    """
    px, py, pz = (jnp.asarray(p, jnp.float32) for p in pauli_error_probs)
    qx, qy, qz = (jnp.asarray(q, jnp.float32) for q in tilt_probs)
    u = jax.random.uniform(key, shape, dtype=jnp.float32)
    is_z = u < qz
    is_x = (u >= qz) & (u < qz + qx)
    is_y = (u >= qz + qx) & (u < qz + qx + qy)
    error_x = (is_x | is_y).astype(jnp.uint8)
    error_z = (is_z | is_y).astype(jnp.uint8)
    # per-site log ratio selected by outcome (where-select, not multiply:
    # an impossible branch's NaN/-inf must not leak into taken branches)
    lr_i = jnp.log1p(-(px + py + pz)) - jnp.log1p(-(qx + qy + qz))
    lw = jnp.where(
        is_z, jnp.log(pz) - jnp.log(qz),
        jnp.where(is_x, jnp.log(px) - jnp.log(qx),
                  jnp.where(is_y, jnp.log(py) - jnp.log(qy), lr_i)))
    return error_x, error_z, _shot_sum(lw)


def bit_flips_tilted(key, shape, p, q):
    """Bernoulli flips drawn at the TILTED rate ``q`` with the per-shot log
    importance weight toward the target rate ``p``.

    Returns ``(flips, log_weight)``; same uniform draw as ``bit_flips``, so
    ``q == p`` is bit-identical with exactly-zero log weight."""
    p = jnp.asarray(p, jnp.float32)
    q = jnp.asarray(q, jnp.float32)
    u = jax.random.uniform(key, shape, dtype=jnp.float32)
    flipped = u < q
    lw = jnp.where(flipped, jnp.log(p) - jnp.log(q),
                   jnp.log1p(-p) - jnp.log1p(-q))
    return flipped.astype(jnp.uint8), _shot_sum(lw)


def depolarizing_xz_tilted_packed(key, shape, pauli_error_probs, tilt_probs):
    """Bit-packed ``depolarizing_xz_tilted``: identical draws and log
    weights, error planes packed 32 shots per uint32 lane word.  Returns
    ``(error_x_packed, error_z_packed, log_weight)`` with the log-weight
    plane staying per-shot ``(batch,)`` float32 (weights don't pack)."""
    from ..ops.gf2_packed import pack_shots

    error_x, error_z, logw = depolarizing_xz_tilted(
        key, shape, pauli_error_probs, tilt_probs)
    return pack_shots(error_x), pack_shots(error_z), logw


def bit_flips_tilted_packed(key, shape, p, q):
    """Bit-packed ``bit_flips_tilted`` (same draws/weights, packed plane)."""
    from ..ops.gf2_packed import pack_shots

    flips, logw = bit_flips_tilted(key, shape, p, q)
    return pack_shots(flips), logw


# ---------------------------------------------------------------------------
# Fixed-weight strata (subset-splitting substrate)
# ---------------------------------------------------------------------------
def fixed_weight_flips(key, shape, k):
    """Uniformly-random weight-``k`` bit patterns, one per shot.

    ``shape = (batch, n)``; ``k`` may be TRACED (one compiled program
    serves every stratum of a sweep).  Each row is a uniform draw from the
    ``C(n, k)`` weight-k strings: a per-shot random permutation assigns
    ranks and the ``k`` smallest ranks flip — exact (no ties), at
    O(n log n) per shot."""
    batch, n = shape
    ranks = jax.vmap(lambda kk: jax.random.permutation(kk, n))(
        jax.random.split(key, batch))
    return (ranks < jnp.asarray(k, jnp.int32)).astype(jnp.uint8)


def stratum_log_weight(n, k, p_total):
    """Log importance weight of a uniform weight-``k`` stratum sample
    toward an i.i.d. total-error-rate-``p_total`` channel:
    ``log C(n,k) + k log p + (n-k) log(1-p)`` — constant across the
    stratum (proposal ``1/C(n,k)`` per pattern, target
    ``(p/3-ish per type)^k (1-p)^(n-k)`` with the per-type factors handled
    by the type draw in ``depolarizing_xz_stratum``).  Traced-``k`` safe
    via ``gammaln``."""
    from jax.scipy.special import gammaln

    n = jnp.asarray(n, jnp.float32)
    k = jnp.asarray(k, jnp.float32)
    p = jnp.asarray(p_total, jnp.float32)
    log_comb = gammaln(n + 1) - gammaln(k + 1) - gammaln(n - k + 1)
    return log_comb + k * jnp.log(p) + (n - k) * jnp.log1p(-p)


def depolarizing_xz_stratum(key, shape, pauli_error_probs, k):
    """Depolarizing sample conditioned on TOTAL error weight ``k``: ``k``
    uniformly-chosen sites get a Pauli drawn from the renormalized
    ``(px, py, pz)`` type distribution; the rest are identity.

    Returns ``(error_x, error_z, log_weight)`` with ``log_weight`` the
    per-shot ``(batch,)`` log importance weight toward the unconditioned
    channel — constant ``stratum_log_weight(n, k, px+py+pz)`` (the type
    draw cancels exactly between proposal and target, leaving the
    position/weight factor).  ``k`` may be traced."""
    batch, n = shape
    k_pos, k_type = jax.random.split(key)
    px, py, pz = (jnp.asarray(p, jnp.float32) for p in pauli_error_probs)
    total = px + py + pz
    sites = fixed_weight_flips(k_pos, shape, k)
    # type draw with the reference's binning order on renormalized probs
    u = jax.random.uniform(k_type, shape, dtype=jnp.float32)
    tz, tx = pz / total, px / total
    is_z = u < tz
    is_x = (u >= tz) & (u < tz + tx)
    is_y = ~(is_z | is_x)
    on = sites.astype(bool)
    error_x = (on & (is_x | is_y)).astype(jnp.uint8)
    error_z = (on & (is_z | is_y)).astype(jnp.uint8)
    logw = jnp.broadcast_to(stratum_log_weight(n, k, total), (batch,))
    return error_x, error_z, logw
