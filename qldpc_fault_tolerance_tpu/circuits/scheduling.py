"""CX-gate scheduling for stabilizer-extraction circuits.

Host-side, one-time-per-code.  Two generators with the same output contract as
the reference (src/CircuitScheduling.py): a list of per-timestep dicts
``{check_index: qubit_index}`` — at timestep t each listed check's ancilla
interacts with its listed data qubit.

* ``ColorationCircuit(H)`` — proper bipartite edge coloring, so every qubit
  and every ancilla is touched at most once per timestep.  The reference pads
  the Tanner graph to a Δ-regular bipartite graph and peels Hopcroft–Karp
  perfect matchings (src/CircuitScheduling.py:8-110); here we use König's
  constructive edge-coloring (color one edge at a time, repairing conflicts
  by swapping colors along an alternating path), which always achieves depth
  exactly Δ = max degree of the Tanner graph — never worse than the
  reference's padded-graph depth, and with no padding heuristics to get stuck.
* ``RandomCircuit(H)`` — each check's neighborhood in an independently
  shuffled order (seed 30000+i for check i, matching the reference's fixed
  seeds, src/CircuitScheduling.py:116-131); depth = max stabilizer weight,
  with no collision avoidance on the data-qubit side.
"""
from __future__ import annotations

import random

import numpy as np

__all__ = ["ColorationCircuit", "RandomCircuit", "validate_schedule"]


def _first_free(used: dict) -> int:
    col = 0
    while col in used:
        col += 1
    return col


def ColorationCircuit(H) -> list[dict[int, int]]:
    """Edge-coloring CX schedule (depth = max Tanner-graph degree)."""
    H = np.asarray(H)
    num_checks, num_qubits = H.shape
    check_edges: list[dict[int, int]] = [{} for _ in range(num_checks)]  # color -> qubit
    qubit_edges: list[dict[int, int]] = [{} for _ in range(num_qubits)]  # color -> check

    for c in range(num_checks):
        for q in np.flatnonzero(H[c]).tolist():
            a = _first_free(check_edges[c])
            if a not in qubit_edges[q]:
                check_edges[c][a] = q
                qubit_edges[q][a] = c
                continue
            b = _first_free(qubit_edges[q])
            # a is free at the check but used at the qubit; swap colors a<->b
            # along the a,b-alternating path starting from q — in a bipartite
            # graph that path cannot terminate at c (parity of the color
            # sequence), so after the swap a is free at both endpoints
            path = []  # (check, qubit, color) edges along the walk
            node, on_qubit, col = q, True, a
            while True:
                nxt = (qubit_edges[node] if on_qubit else check_edges[node]).get(col)
                if nxt is None:
                    break
                path.append((nxt, node, col) if on_qubit else (node, nxt, col))
                node, on_qubit, col = nxt, not on_qubit, (b if col == a else a)
            for pc, pq, pcol in path:
                del check_edges[pc][pcol]
                del qubit_edges[pq][pcol]
            for pc, pq, pcol in path:
                new = b if pcol == a else a
                check_edges[pc][new] = pq
                qubit_edges[pq][new] = pc
            check_edges[c][a] = q
            qubit_edges[q][a] = c

    depth = max((max(d, default=-1) for d in check_edges), default=-1) + 1
    return [
        {c: check_edges[c][t] for c in range(num_checks) if t in check_edges[c]}
        for t in range(depth)
    ]


def ColorationCircuitHK(H) -> list[dict[int, int]]:
    """The reference's exact coloration schedule (src/CircuitScheduling.py:
    8-110): pad the Tanner graph to a Δ-regular bipartite graph (dummy check
    nodes, then greedy dummy edges in node-insertion order), then repeatedly
    peel Hopcroft–Karp maximum matchings off the padded graph, keeping each
    matching's real-check pairs as one timestep.

    This reproduces the reference's *timestep structure*, which is
    physics-relevant at circuit level (it fixes which CX hook errors align
    across checks).  Two behavioral quirks are preserved deliberately:

      * matchings are peeled until the PADDED graph is empty, so the depth
        can exceed Δ of the real graph and timesteps can be sparse;
      * a real check with degree < Δ receives dummy edges to real qubits,
        and a matching may pair it through such a dummy edge — the resulting
        {check: qubit} entry is NOT a Tanner edge (the reference schedules
        this spurious CX too; ``validate_schedule`` therefore does not apply
        to this generator for irregular H).

    Determinism: node/edge insertion orders and the greedy padding loop
    mirror the reference exactly; ``hopcroft_karp_matching`` and small-int
    set iteration are deterministic, so the schedule is reproducible.
    """
    import networkx as nx
    from networkx.algorithms import bipartite as nx_bipartite

    H = np.asarray(H)
    num_checks, num_bits = H.shape
    g = nx.Graph()
    c_nodes = [-(i + 1) for i in range(num_checks)]
    v_nodes = [j + 1 for j in range(num_bits)]
    g.add_nodes_from(c_nodes, bipartite=0)
    g.add_nodes_from(v_nodes, bipartite=1)
    g.add_edges_from(
        (-(i + 1), j + 1)
        for i in range(num_checks)
        for j in range(num_bits)
        if H[i][j] == 1
    )

    # pad: dummy check nodes up to the qubit count, then greedy dummy edges
    # (first open check x first open qubit, re-scanned in insertion order)
    # until every node reaches Δ = max degree
    gs = g.copy()
    gs.add_nodes_from(
        (-(i + 1) for i in range(num_checks, num_bits)), bipartite=0)
    delta = max(d for _, d in gs.degree)
    open_deg = {node: deg for node, deg in dict(gs.degree()).items()
                if deg < delta}
    while open_deg:
        added = 0
        for c in [n for n in open_deg if n < 0]:
            for v in [n for n in open_deg if n > 0]:
                if not gs.has_edge(c, v):
                    gs.add_edge(c, v)
                    added += 1
                    for node in (c, v):
                        if open_deg[node] + 1 == delta:
                            open_deg.pop(node)
                        else:
                            open_deg[node] += 1
                    break
        if not added:
            # every open check already touches every open qubit; the greedy
            # padding cannot reach Δ-regularity (the reference's loop spins
            # forever here) — fail loudly instead
            raise ValueError(
                "coloration_hk: Δ-regular padding is infeasible for this H "
                "(greedy dummy-edge pass made no progress); use "
                "circuit_type='coloration'"
            )

    # peel maximum matchings; keep real-check pairs per timestep
    real_c = {n for n, d in g.nodes(data=True) if d["bipartite"] == 0}
    all_c = {n for n, d in gs.nodes(data=True) if d["bipartite"] == 0}
    schedule = []
    while gs.number_of_edges() > 0:
        bm = nx_bipartite.matching.hopcroft_karp_matching(gs, list(all_c))
        schedule.append({-c - 1: bm[c] - 1 for c in bm if c in real_c})
        gs.remove_edges_from([(c, bm[c]) for c in bm if c in all_c])
    return schedule


def RandomCircuit(H) -> list[dict[int, int]]:
    """Shuffled-neighborhood schedule (reference src/CircuitScheduling.py:116-131).

    Keeps the reference's deterministic per-check seeds (30000 + check index)
    so schedules are reproducible across runs and implementations.
    """
    H = np.asarray(H)
    num_checks, _ = H.shape
    seed0 = 30000
    orders = [list(np.flatnonzero(H[i])) for i in range(num_checks)]
    for i, order in enumerate(orders):
        random.Random(seed0 + i).shuffle(order)
    depth = max((len(o) for o in orders), default=0)
    return [
        {i: orders[i][t] for i in range(num_checks) if len(orders[i]) > t}
        for t in range(depth)
    ]


def validate_schedule(H, schedule, require_disjoint_qubits: bool = True) -> None:
    """Check a schedule covers exactly the Tanner edges, each ancilla used at
    most once per timestep, and (optionally) each qubit at most once per
    timestep.  Raises AssertionError on violation."""
    H = np.asarray(H)
    seen = set()
    for step in schedule:
        qubits = list(step.values())
        assert len(set(step.keys())) == len(step), "duplicate check in timestep"
        if require_disjoint_qubits:
            assert len(set(qubits)) == len(qubits), "qubit reused within a timestep"
        for c, q in step.items():
            assert H[c, q] == 1, f"({c},{q}) is not a Tanner edge"
            assert (c, q) not in seen, f"edge ({c},{q}) scheduled twice"
            seen.add((c, q))
    expected = {(i, j) for i, j in zip(*np.nonzero(H))}
    assert seen == expected, "schedule does not cover all Tanner edges"
