"""Stabilizer-circuit IR with a stim-compatible text round-trip.

The reference drives everything through ``stim.Circuit`` and its *text* form:
circuits are composed with ``+`` / ``*``, noise is injected by regex rewrites
of ``str(circuit)`` (src/ErrorPlugin.py), and the space-time decoder consumes
the text of ``circuit.detector_error_model(...)``.  This module provides the
same surface without stim: a minimal instruction list, ``append`` with stim's
argument conventions, text emission/parsing, and REPEAT blocks (kept
structured so the TPU sampler can ``lax.scan`` over them instead of unrolling).

Supported instructions (all the reference emits, src/Simulators.py:438-609,
src/Simulators_SpaceTime.py:737-941): R, RX, H, CX, CZ, M, MR, MX, TICK,
X_ERROR, Y_ERROR, Z_ERROR, DEPOLARIZE1, DEPOLARIZE2, DETECTOR,
OBSERVABLE_INCLUDE, SHIFT_COORDS, and REPEAT blocks.
"""
from __future__ import annotations

import dataclasses
import re

__all__ = ["Circuit", "Instruction", "RepeatBlock", "RecTarget", "target_rec"]

GATE_NAMES = {"R", "RX", "H", "CX", "CZ", "M", "MR", "MX", "TICK"}
NOISE_NAMES = {"X_ERROR", "Y_ERROR", "Z_ERROR", "DEPOLARIZE1", "DEPOLARIZE2"}
ANNOTATION_NAMES = {"DETECTOR", "OBSERVABLE_INCLUDE", "SHIFT_COORDS"}
MEASUREMENT_NAMES = {"M", "MR", "MX"}
TWO_QUBIT_NAMES = {"CX", "CZ"}


@dataclasses.dataclass(frozen=True)
class RecTarget:
    """A measurement-record lookback target (stim's ``rec[-k]``)."""

    offset: int

    def __post_init__(self):
        if self.offset >= 0:
            raise ValueError("measurement record targets must be negative lookbacks")

    def __str__(self):
        return f"rec[{self.offset}]"


def target_rec(offset: int) -> RecTarget:
    """stim.target_rec equivalent."""
    return RecTarget(int(offset))


@dataclasses.dataclass(frozen=True)
class Instruction:
    name: str
    targets: tuple  # ints (qubits) or RecTargets (record lookbacks)
    args: tuple  # parenthesised float arguments

    def __str__(self):
        out = self.name
        if self.args:
            out += "(" + ", ".join(_fmt_arg(a) for a in self.args) + ")"
        for t in self.targets:
            out += " " + str(t)
        return out


@dataclasses.dataclass
class RepeatBlock:
    repeat_count: int
    body: "Circuit"

    def __str__(self):
        inner = "\n".join("    " + line for line in str(self.body).splitlines())
        return f"REPEAT {self.repeat_count} {{\n{inner}\n}}"


def fmt_float(a: float) -> str:
    """Public fixed-point float formatter for building instruction strings
    (e.g. ``f"DEPOLARIZE2({fmt_float(p)})"``) — never scientific notation, so
    tiny probabilities survive the text round-trip."""
    return _fmt_arg(a)


def _fmt_arg(a: float) -> str:
    """Fixed-point float formatting: the reference DEM/noise parsers match
    ``\\d+\\.\\d+`` (src/Simulators_SpaceTime.py:575), so never emit scientific
    notation and always keep a decimal point."""
    if a == int(a):
        return f"{int(a)}" if abs(a) < 1e15 else f"{a:.1f}"
    s = f"{a:.12f}".rstrip("0")
    if s.endswith("."):
        s += "0"
    return s


def _canon_name(name: str) -> str:
    name = name.upper()
    if name == "DETECTOR" or name == "OBSERVABLE_INCLUDE" or name in GATE_NAMES \
            or name in NOISE_NAMES or name == "SHIFT_COORDS" or name == "REPEAT":
        return name
    raise ValueError(f"unsupported instruction: {name}")


class Circuit:
    """A sequence of Instructions and RepeatBlocks."""

    def __init__(self, text: str | None = None):
        self.items: list[Instruction | RepeatBlock] = []
        if text:
            self._parse(text)

    # ------------------------------------------------------------- building
    def append(self, name, targets=(), args=None):
        """stim-style append.  ``targets`` may be an int, an iterable of ints,
        or RecTargets; ``args`` a float or tuple of floats."""
        name = _canon_name(str(name))
        if isinstance(targets, (int,)):
            targets = (targets,)
        elif isinstance(targets, RecTarget):
            targets = (targets,)
        targets = tuple(
            t if isinstance(t, RecTarget) else int(t) for t in targets
        )
        if args is None:
            args = ()
        elif isinstance(args, (int, float)):
            args = (float(args),)
        else:
            args = tuple(float(a) for a in args)
        if name in TWO_QUBIT_NAMES and len(targets) % 2:
            raise ValueError(f"{name} needs an even number of targets")
        if name in ("DETECTOR", "OBSERVABLE_INCLUDE"):
            if not all(isinstance(t, RecTarget) for t in targets):
                raise ValueError(f"{name} targets must be measurement records")
        self.items.append(Instruction(name, targets, args))
        return self

    def __iadd__(self, other: "Circuit"):
        self.items.extend(other.copy().items)
        return self

    def __add__(self, other: "Circuit") -> "Circuit":
        out = self.copy()
        out.items.extend(other.copy().items)
        return out

    def __mul__(self, n: int) -> "Circuit":
        out = Circuit()
        n = int(n)
        if n < 0:
            raise ValueError("repeat count must be non-negative")
        if n == 0 or not self.items:
            return out
        if n == 1:
            return self.copy()
        out.items.append(RepeatBlock(n, self.copy()))
        return out

    __rmul__ = __mul__

    def detector_error_model(self, flatten_loops: bool = True):
        """stim-parity surface: notebooks call
        ``circuit.detector_error_model(flatten_loops=True)`` directly
        (SpaceTimeDecodingDemo cell 4)."""
        from .dem import detector_error_model

        return detector_error_model(self, flatten_loops=flatten_loops)

    def copy(self) -> "Circuit":
        out = Circuit()
        for item in self.items:
            if isinstance(item, RepeatBlock):
                out.items.append(RepeatBlock(item.repeat_count, item.body.copy()))
            else:
                out.items.append(item)
        return out

    # ------------------------------------------------------------ analysis
    def flattened(self):
        """Yield instructions with REPEAT blocks unrolled."""
        for item in self.items:
            if isinstance(item, RepeatBlock):
                for _ in range(item.repeat_count):
                    yield from item.body.flattened()
            else:
                yield item

    @property
    def num_measurements(self) -> int:
        return sum(
            len(ins.targets) for ins in self.flattened()
            if ins.name in MEASUREMENT_NAMES
        )

    @property
    def num_detectors(self) -> int:
        return sum(1 for ins in self.flattened() if ins.name == "DETECTOR")

    @property
    def num_observables(self) -> int:
        obs = [
            int(ins.args[0]) if ins.args else 0
            for ins in self.flattened() if ins.name == "OBSERVABLE_INCLUDE"
        ]
        return (max(obs) + 1) if obs else 0

    @property
    def num_qubits(self) -> int:
        mx = -1
        for ins in self.flattened():
            for t in ins.targets:
                if not isinstance(t, RecTarget):
                    mx = max(mx, t)
        return mx + 1

    # ---------------------------------------------------------------- text
    def __str__(self):
        return "\n".join(str(item) for item in self.items)

    def __repr__(self):
        return f"Circuit(<{len(self.items)} items>)"

    def __eq__(self, other):
        return isinstance(other, Circuit) and str(self) == str(other)

    _INS_RE = re.compile(r"^([A-Za-z_0-9]+)\s*(?:\(([^)]*)\))?\s*(.*)$")

    def _parse(self, text: str):
        lines = text.splitlines()
        stack_circ = [self]
        stack_reps: list[int] = []
        for raw in lines:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if line == "}":
                if len(stack_circ) < 2:
                    raise ValueError("unbalanced '}' in circuit text")
                body = stack_circ.pop()
                rep = stack_reps.pop()
                stack_circ[-1].items.append(RepeatBlock(rep, body))
                continue
            if line.upper().startswith("REPEAT"):
                m = re.match(r"^REPEAT\s+(\d+)\s*\{$", line, re.IGNORECASE)
                if not m:
                    raise ValueError(f"malformed REPEAT line: {raw!r}")
                stack_reps.append(int(m.group(1)))
                stack_circ.append(Circuit())
                continue
            m = self._INS_RE.match(line)
            if not m:
                raise ValueError(f"cannot parse circuit line: {raw!r}")
            name, argstr, targetstr = m.groups()
            args = tuple(
                float(a) for a in argstr.split(",") if a.strip()
            ) if argstr is not None else ()
            targets = []
            for tok in targetstr.split():
                if tok.startswith("rec["):
                    targets.append(RecTarget(int(tok[4:-1])))
                else:
                    targets.append(int(tok))
            stack_circ[-1].append(name, targets, args if args else None)
        if len(stack_circ) != 1:
            raise ValueError("unbalanced REPEAT block in circuit text")
