"""Noise injection by circuit-text rewriting.

Mirrors the reference's rewrite-``str(circuit)``-and-reparse approach
(src/ErrorPlugin.py): each function finds unique instruction lines and splices
error instructions around them.  Only ``AddCXError`` is used on the
reference's main simulation paths (src/Simulators.py:597,
src/Simulators_SpaceTime.py:935-936); the rest are provided for parity.

Conscious fix vs the reference (documented per SURVEY §2.4): the reference's
measurement/reset regexes (``'\\nM .*\\n'`` etc.) consume the surrounding
newlines, so of two *adjacent* M/R lines only one is rewritten.  Here lines
are matched with ^...$ in MULTILINE mode, so every matching line is rewritten.
Probabilities are formatted fixed-point (never scientific) so tiny values
survive the text round-trip.
"""
from __future__ import annotations

import re

from .ir import Circuit, fmt_float

__all__ = [
    "AddCXError",
    "AddCZError",
    "AddMeasurementError",
    "AddResetError",
    "AddIdlingError",
    "AddSingleQubitErrorBeforeRound",
]


def _rewrite_lines(circuit: Circuit, head_re: str, fn) -> Circuit:
    """Rewrite every line whose mnemonic matches ``head_re``; ``fn(line,
    head)`` returns the replacement text (typically the line plus a spliced
    error line)."""
    pattern = re.compile(rf"^\s*({head_re})( .*)?$", re.MULTILINE)
    out = []
    for raw in str(circuit).splitlines():
        m = pattern.match(raw)
        out.append(fn(raw, m.group(1).strip()) if m else raw)
    return Circuit("\n".join(out))


def AddCXError(circuit: Circuit, error_instruction: str) -> Circuit:
    """Append ``error_instruction`` (e.g. ``'DEPOLARIZE2(0.01)'``) on the same
    targets after every CX (src/ErrorPlugin.py:11-25)."""
    return _rewrite_lines(
        circuit, "CX",
        lambda line, head: line + "\n" + line.replace("CX", error_instruction, 1),
    )


def AddCZError(circuit: Circuit, error_instruction: str) -> Circuit:
    """src/ErrorPlugin.py:29-42."""
    return _rewrite_lines(
        circuit, "CZ",
        lambda line, head: line + "\n" + line.replace("CZ", error_instruction, 1),
    )


def AddMeasurementError(circuit: Circuit, meas_p: float) -> Circuit:
    """X_ERROR(p) on the measured qubits immediately before every M / MR
    (src/ErrorPlugin.py:94-113)."""
    err = f"X_ERROR({fmt_float(meas_p)})"
    return _rewrite_lines(
        circuit, "MR|M",
        lambda line, head: line.replace(head, err, 1) + "\n" + line,
    )


def AddResetError(circuit: Circuit, reset_p: float) -> Circuit:
    """X_ERROR(p) on the reset qubits immediately after every R / MR
    (src/ErrorPlugin.py:145-163)."""
    err = f"X_ERROR({fmt_float(reset_p)})"
    return _rewrite_lines(
        circuit, "MR|R",
        lambda line, head: line + "\n" + line.replace(head, err, 1),
    )


def _targets_suffix(error_instruction: str, target_qubit_indices) -> str:
    return error_instruction + " " + " ".join(str(i) for i in target_qubit_indices)


def AddIdlingError(circuit: Circuit, error_instruction: str,
                   target_qubit_indices=()) -> Circuit:
    """Idling errors on ``target_qubit_indices`` after every M / MR
    (src/ErrorPlugin.py:116-142)."""
    if not len(target_qubit_indices):
        return circuit.copy()
    suffix = _targets_suffix(error_instruction, target_qubit_indices)
    return _rewrite_lines(
        circuit, "MR|M", lambda line, head: line + "\n" + suffix
    )


def AddSingleQubitErrorBeforeRound(circuit: Circuit, error_instruction: str,
                                   target_qubit_indices=()) -> Circuit:
    """Single-qubit errors on ``target_qubit_indices`` after every R / MR
    (src/ErrorPlugin.py:70-91 — the second of the two identically-named
    definitions, which shadows the first)."""
    if not len(target_qubit_indices):
        return circuit.copy()
    suffix = _targets_suffix(error_instruction, target_qubit_indices)
    return _rewrite_lines(
        circuit, "MR|R", lambda line, head: line + "\n" + suffix
    )
