"""Noise injection by circuit-text rewriting.

Mirrors the reference's regex-on-``str(circuit)`` approach
(src/ErrorPlugin.py): each rewrite finds unique instruction lines and splices
error instructions around them, then re-parses the text.  Only ``AddCXError``
is used on the reference's main simulation paths (src/Simulators.py:597,
src/Simulators_SpaceTime.py:935-936); the rest are provided for parity.
"""
from __future__ import annotations

import re

from .ir import Circuit

__all__ = [
    "AddCXError",
    "AddCZError",
    "AddMeasurementError",
    "AddResetError",
    "AddIdlingError",
    "AddSingleQubitErrorBeforeRound",
]


def _rewrite(circuit: Circuit, fn) -> Circuit:
    return Circuit(fn(str(circuit) + "\n"))


def _duplicate_after(text: str, line_re: str, old: str, new: str) -> str:
    """After every unique line matching ``line_re``, insert a copy of the line
    with ``old`` replaced by ``new`` (the reference's AddCXError pattern,
    src/ErrorPlugin.py:11-25)."""
    for ins in set(re.findall(line_re, text)):
        text = text.replace(ins, ins + ins.replace(old, new))
    return text


def AddCXError(circuit: Circuit, error_instruction: str) -> Circuit:
    """Append ``error_instruction`` (e.g. ``'DEPOLARIZE2(0.01)'``) on the same
    targets after every CX (src/ErrorPlugin.py:11-25)."""
    return _rewrite(
        circuit, lambda s: _duplicate_after(s, r"CX.*\n", "CX", error_instruction)
    )


def AddCZError(circuit: Circuit, error_instruction: str) -> Circuit:
    """src/ErrorPlugin.py:29-42."""
    return _rewrite(
        circuit, lambda s: _duplicate_after(s, r"CZ.*\n", "CZ", error_instruction)
    )


def AddMeasurementError(circuit: Circuit, meas_p: float) -> Circuit:
    """X_ERROR(p) on the measured qubits immediately before every M / MR
    (src/ErrorPlugin.py:94-113)."""

    def fn(text: str) -> str:
        lines = (re.findall(r"\nM .*\n", text) + re.findall(r" M .*\n", text)
                 + re.findall(r"\nMR .*\n", text) + re.findall(r" MR .*\n", text))
        for ins in set(lines):
            head = "MR" if "MR" in ins else "M"
            text = text.replace(ins, ins.replace(head, f"X_ERROR({meas_p:f})") + ins)
        return text

    return _rewrite(circuit, fn)


def AddResetError(circuit: Circuit, reset_p: float) -> Circuit:
    """X_ERROR(p) on the reset qubits immediately after every R / MR
    (src/ErrorPlugin.py:145-163)."""

    def fn(text: str) -> str:
        lines = (re.findall(r"\nR .*\n", text) + re.findall(r" R .*\n", text)
                 + re.findall(r"\nMR .*\n", text) + re.findall(r" MR .*\n", text))
        for ins in set(lines):
            head = "MR" if "MR" in ins else "R"
            text = text.replace(ins, ins + ins.replace(head, f"X_ERROR({reset_p:f})"))
        return text

    return _rewrite(circuit, fn)


def AddIdlingError(circuit: Circuit, error_instruction: str,
                   target_qubit_indices=()) -> Circuit:
    """Idling errors on ``target_qubit_indices`` after every M / MR
    (src/ErrorPlugin.py:116-142)."""
    suffix = error_instruction + " " + "".join(
        f"{i} " for i in target_qubit_indices
    ) + "\n"

    def fn(text: str) -> str:
        lines = (re.findall(r"\nM .*\n", text) + re.findall(r" M .*\n", text)
                 + re.findall(r"\nMR .*\n", text) + re.findall(r" MR .*\n", text))
        for ins in set(lines):
            text = text.replace(ins, ins + suffix)
        return text

    return _rewrite(circuit, fn) if target_qubit_indices else _rewrite(circuit, lambda s: s)


def AddSingleQubitErrorBeforeRound(circuit: Circuit, error_instruction: str,
                                   target_qubit_indices=()) -> Circuit:
    """Single-qubit errors on ``target_qubit_indices`` after every R / MR
    (src/ErrorPlugin.py:70-91 — the second of the two identically-named
    definitions, which shadows the first)."""
    if not target_qubit_indices:
        return circuit.copy()
    suffix = error_instruction + " " + "".join(
        f"{i} " for i in target_qubit_indices
    ) + "\n"

    def fn(text: str) -> str:
        lines = (re.findall(r"\nR .*\n", text) + re.findall(r" R .*\n", text)
                 + re.findall(r"\nMR .*\n", text) + re.findall(r" MR .*\n", text))
        for ins in set(lines):
            text = text.replace(ins, ins + suffix)
        return text

    return _rewrite(circuit, fn)
