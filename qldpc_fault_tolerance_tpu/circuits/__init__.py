"""Circuit layer: IR, CX scheduling, noise plugin, TPU detector sampler, DEM.

Replaces the reference's stim dependency (circuit IR + detector sampling +
detector-error-model derivation, src/Simulators.py:386-671,
src/Simulators_SpaceTime.py:672-1077) and its host-side schedulers
(src/CircuitScheduling.py) with a self-contained TPU-native stack:

  scheduling    host-side CX schedule generation (coloration / random)
  ir            stabilizer-circuit IR with stim-compatible text round-trip
  error_plugin  circuit-text noise rewrites (AddCXError & friends)
  sampler       vectorized Pauli-frame detector sampler (jit/vmap, lax.scan
                over REPEAT blocks)
  dem           detector-error-model derivation + fault-hypergraph extraction
"""
from .scheduling import ColorationCircuit, ColorationCircuitHK, RandomCircuit, validate_schedule
from .ir import Circuit, target_rec
from .error_plugin import (
    AddCXError,
    AddCZError,
    AddMeasurementError,
    AddResetError,
    AddIdlingError,
    AddSingleQubitErrorBeforeRound,
)
from .sampler import FrameSampler
from .dem import (
    DetectorErrorModel,
    detector_error_model,
    GenFaultHyperGraph,
    GenCorrecHyperGraph,
)

__all__ = [
    "ColorationCircuit",
    "ColorationCircuitHK",
    "RandomCircuit",
    "validate_schedule",
    "Circuit",
    "target_rec",
    "AddCXError",
    "AddCZError",
    "AddMeasurementError",
    "AddResetError",
    "AddIdlingError",
    "AddSingleQubitErrorBeforeRound",
    "FrameSampler",
    "DetectorErrorModel",
    "detector_error_model",
    "GenFaultHyperGraph",
    "GenCorrecHyperGraph",
]
