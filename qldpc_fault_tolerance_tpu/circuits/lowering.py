"""Lowering of the circuit IR to fused, vectorizable primitive ops.

The sampler (device, random) and the detector-error-model builder (host,
deterministic) share this compiled form, so fault propagation and sampling
agree by construction.

Compilation steps:
  1. walk the IR, resolving DETECTOR / OBSERVABLE_INCLUDE record lookbacks to
     absolute measurement-record columns (REPEAT blocks contribute contiguous
     record ranges);
  2. lower gates/noise to primitive ops with explicit target index arrays and
     *absolute* record columns on measurement ops (so op order no longer
     encodes record order);
  3. fuse ops: an op may migrate backward past ops whose qubit support is
     disjoint from its own and merge into an earlier op with the same kind and
     args — disjoint-support ops commute, so this is semantics-preserving.
     CX/CZ additionally refuse a merge that would put one qubit on both the
     control and target side (shared controls or shared targets are fine:
     the fused update uses XOR-accumulating scatters).  This collapses the
     reference's CX / DEPOLARIZE2 interleave (AddCXError emits one noise line
     per gate line) into one gate op + one noise op per scheduling layer.

Zero-probability noise ops are dropped (the notebooks routinely pass
p_i = p_state_p = 0, src demo cell 2).
"""
from __future__ import annotations

import dataclasses
import hashlib
import re
import threading
import warnings
from collections import OrderedDict

import numpy as np

from .ir import (
    Circuit,
    Instruction,
    MEASUREMENT_NAMES,
    NOISE_NAMES,
    RecTarget,
    RepeatBlock,
)

__all__ = ["Op", "Segment", "CompiledCircuit", "compile_circuit"]


@dataclasses.dataclass
class Op:
    """One fused primitive op.

    kind:
      'cx'/'cz'    a, b: control/target index arrays
      'h'          a: qubit indices (x/z swap)
      'reset'      a: qubit indices (frame cleared; covers R and RX)
      'measure'    a: qubit indices; basis 'z' (M/MR: record x-frame) or
                   'x' (MX: record z-frame); rec: absolute record columns;
                   reset_after: MR; collapse: randomize conjugate frame (M/MX)
      'dep1'       a, p: single-qubit depolarizing (X/Y/Z each p/3)
      'dep2'       a, b, p: two-qubit depolarizing (15 components, p/15 each)
      'perr'       a, p, fx, fz: Pauli error (X_ERROR: fx; Z_ERROR: fz;
                   Y_ERROR: both)
    """

    kind: str
    a: np.ndarray
    b: np.ndarray | None = None
    p: float = 0.0
    basis: str = "z"
    rec: np.ndarray | None = None
    reset_after: bool = False
    collapse: bool = False
    fx: bool = False
    fz: bool = False
    noise_id: int = -1

    @property
    def is_random(self) -> bool:
        return self.kind in ("dep1", "dep2", "perr") or (
            self.kind == "measure" and self.collapse and not self.reset_after
        )

    def support(self) -> frozenset:
        s = set(self.a.tolist())
        if self.b is not None:
            s |= set(self.b.tolist())
        return frozenset(s)


@dataclasses.dataclass
class Segment:
    """A run of ops executed once ('block') or scanned ('repeat')."""

    kind: str  # 'block' | 'repeat'
    ops: list[Op]
    repeat_count: int = 1
    meas_per_iter: int = 0  # record width contributed by one iteration
    rec_offset: int = 0  # absolute record column of this segment's first bit


@dataclasses.dataclass
class CompiledCircuit:
    num_qubits: int
    num_measurements: int
    num_detectors: int
    num_observables: int
    segments: list[Segment]
    # detector d = XOR of record columns det_cols[d]; same for observables
    det_cols: list[list[int]]
    obs_cols: list[list[int]]
    # text-emission metadata for the DEM: ('shift',) and ('det', det_index,
    # args) events in circuit order, only for detectors carrying args
    coord_events: list[tuple]

    def structure_key(self) -> str:
        """Digest of the circuit *structure* — every field the sampler bakes
        into its traced program EXCEPT the noise probabilities ``op.p``
        (which ride in as traced arguments).  Two compiled circuits with
        equal keys lower to the identical XLA program, so a p-sweep over one
        memory-circuit layout shares a single compiled sampler
        (sampler.py's module cache)."""
        import hashlib

        h = hashlib.sha256()

        def put(*vals):
            # each value is framed (type tag + shape/dtype for arrays + a
            # terminator) so adjacent fields can never alias across
            # boundaries — ints (1, 23) vs (12, 3) must hash differently
            for v in vals:
                if isinstance(v, np.ndarray):
                    h.update(f"a{v.dtype}{v.shape}|".encode())
                    h.update(v.tobytes())
                else:
                    h.update(f"v{v!r}".encode())
                h.update(b";")

        put(self.num_qubits, self.num_measurements, self.num_detectors,
            self.num_observables)
        for seg in self.segments:
            put(seg.kind, seg.repeat_count, seg.meas_per_iter, seg.rec_offset)
            for op in seg.ops:
                put(op.kind, op.a, op.b if op.b is not None else "-",
                    op.basis, op.rec if op.rec is not None else "-",
                    op.reset_after, op.collapse, op.fx, op.fz, op.noise_id)
        for cols in self.det_cols:
            put(cols)
        for cols in self.obs_cols:
            put(cols)
        return h.hexdigest()

    def flattened_ops(self):
        """Ops with repeat segments unrolled; measurement record columns
        shifted per iteration.  Yields (op, unrolled_index)."""
        i = 0
        for seg in self.segments:
            for it in range(seg.repeat_count if seg.kind == "repeat" else 1):
                for op in seg.ops:
                    if op.kind == "measure" and seg.kind == "repeat":
                        op = dataclasses.replace(
                            op, rec=op.rec + seg.rec_offset + it * seg.meas_per_iter
                        )
                    elif op.kind == "measure":
                        op = dataclasses.replace(op, rec=op.rec + seg.rec_offset)
                    yield op, i
                    i += 1


def _mergeable(into: Op, op: Op) -> bool:
    if into.kind != op.kind:
        return False
    if into.kind in ("dep1", "dep2", "perr"):
        # disjoint support required: the scatter-free sampler applies fused
        # noise via membership masks, which would collapse a repeated qubit's
        # k independent channel applications into one
        return (into.p == op.p and into.fx == op.fx and into.fz == op.fz
                and not (into.support() & op.support()))
    if into.kind in ("cx", "cz"):
        # one side may repeat, but no qubit may sit on both sides of the
        # fused op (that would reorder a read-after-write)
        a = set(into.a.tolist()) | set(op.a.tolist())
        b = set(into.b.tolist()) | set(op.b.tolist())
        return not (a & b)
    if into.kind in ("h", "reset"):
        return not (into.support() & op.support())
    if into.kind == "measure":
        return (
            into.basis == op.basis
            and into.reset_after == op.reset_after
            and into.collapse == op.collapse
            and not (into.support() & op.support())
        )
    return False


def _merge(into: Op, op: Op) -> Op:
    a = np.concatenate([into.a, op.a])
    b = None if into.b is None else np.concatenate([into.b, op.b])
    rec = None if into.rec is None else np.concatenate([into.rec, op.rec])
    return dataclasses.replace(into, a=a, b=b, rec=rec)


def _fuse(ops: list[Op]) -> list[Op]:
    fused: list[Op] = []
    supports: list[frozenset] = []
    for op in ops:
        sup = op.support()
        merged = False
        # migrate backward past disjoint ops; merge into a compatible one
        for j in range(len(fused) - 1, -1, -1):
            if _mergeable(fused[j], op):
                fused[j] = _merge(fused[j], op)
                supports[j] = supports[j] | sup
                merged = True
                break
            if supports[j] & sup:
                break
        if not merged:
            fused.append(op)
            supports.append(sup)
    return fused


def _lower_instruction(ins: Instruction, rec_base: int):
    """Lower one IR instruction to zero, one, or a list of proto-ops.
    rec_base is the
    measurement count before this instruction (for record columns relative to
    the enclosing segment)."""
    name = ins.name
    q = np.asarray([t for t in ins.targets if not isinstance(t, RecTarget)], dtype=np.int32)
    if name == "TICK" or name in ("DETECTOR", "OBSERVABLE_INCLUDE", "SHIFT_COORDS"):
        return None
    if name in ("R", "RX"):
        return Op("reset", q)
    if name == "H":
        return Op("h", q)
    if name in ("CX", "CZ"):
        a, b = q[0::2], q[1::2]
        if name == "CX" and set(a.tolist()) & set(b.tolist()):
            # Chained pairs sharing a qubit across sides ('CX 0 1 1 2'):
            # stim applies the pairs left to right, so a later pair must see
            # the frame already updated by an earlier one.  A single fused
            # scatter op would read pre-update values — split into
            # sequential per-pair ops (_fuse re-merges only the safe ones).
            # CZ needs no split: it only reads x-frames and writes z-frames,
            # so the fused add-scatter is order-independent.
            return [
                Op(name.lower(), a[i : i + 1], b[i : i + 1])
                for i in range(len(a))
            ]
        return Op(name.lower(), a, b)
    if name in ("M", "MR", "MX"):
        rec = np.arange(rec_base, rec_base + len(q), dtype=np.int32)
        return Op(
            "measure", q, basis="x" if name == "MX" else "z", rec=rec,
            reset_after=(name == "MR"), collapse=(name != "MR"),
        )
    if name in ("X_ERROR", "Y_ERROR", "Z_ERROR", "DEPOLARIZE1", "DEPOLARIZE2"):
        p = float(ins.args[0]) if ins.args else 0.0
        if p == 0.0 or len(q) == 0:
            return None
        if name == "DEPOLARIZE1":
            return Op("dep1", q, p=p)
        if name == "DEPOLARIZE2":
            return Op("dep2", q[0::2], q[1::2], p=p)
        return Op(
            "perr", q, p=p,
            fx=name in ("X_ERROR", "Y_ERROR"), fz=name in ("Z_ERROR", "Y_ERROR"),
        )
    raise ValueError(f"cannot lower instruction {name}")


_NOISE_ARG_RE = re.compile(
    r"^(\s*(?:X_ERROR|Y_ERROR|Z_ERROR|DEPOLARIZE1|DEPOLARIZE2))\(([^)]+)\)",
    re.M,
)

# digest -> lowered template; keyed on sha256 of the canonical text so the
# memo does not pin multi-MB circuit strings (hgp-sized circuits are ~70k
# instruction lines).  functools.lru_cache does not fit: the value is built
# from the canonical TEXT while the key must be its digest.
_TEMPLATE_CACHE: "OrderedDict[str, CompiledCircuit]" = OrderedDict()
_TEMPLATE_CACHE_MAX = 32
_TEMPLATE_CACHE_LOCK = threading.Lock()


def _freeze_template_arrays(template: CompiledCircuit) -> None:
    """Templates share their index arrays (op targets, rec columns) with
    every instantiation compile_circuit returns — an in-place write through
    any of them would corrupt the cache and all sibling instantiations, so
    make numpy raise instead."""
    for seg in template.segments:
        for op in seg.ops:
            for arr in (op.a, op.b, op.rec):
                if arr is not None:
                    arr.setflags(write=False)


def compile_circuit(circuit: Circuit) -> CompiledCircuit:
    """Lower a circuit, memoizing the expensive passes on the circuit's
    p-CANONICALIZED text.

    A threshold sweep lowers the same memory-circuit layout once per
    (code, p, seed) cell — seconds of pure Python each for hgp-sized
    circuits (~70k instructions), differing only in the noise-probability
    literals.  The canonical form replaces each distinct nonzero
    probability with its first-occurrence index (1, 2, ...), which
    preserves BOTH lowering-relevant properties of the probabilities: the
    zero/nonzero pattern (zero-p ops are dropped) and the equality pattern
    (_mergeable fuses noise ops only at equal p).  The lowered template is
    cached on the canonical text's sha256; instantiation rewrites only the
    fused noise ops' ``p`` values (index -> actual probability), sharing
    every index array.

    Probability precision: canonicalization reads the probabilities from
    the circuit's TEXT form, whose fixed-point float format carries 12
    decimals (ir._fmt_arg) — probabilities are distinguished (and
    preserved) to 1e-12, far below any physical operating point; a nonzero
    p that formats to 0 would be dropped like an explicit zero.
    """
    text = str(circuit)
    values: list[float] = []
    ids: dict[float, int] = {}
    saw_zero_noise = False

    def _sub(m):
        # the package emits exactly one argument per noise instruction; a
        # multi-arg line would silently corrupt the index mapping below, so
        # fail loudly instead of guessing
        f = float(m.group(2).strip())
        if f == 0.0:
            nonlocal saw_zero_noise
            saw_zero_noise = True
            return m.group(0)
        if f not in ids:
            ids[f] = len(values) + 1
            values.append(f)
        return f"{m.group(1)}({ids[f]})"

    canon = _NOISE_ARG_RE.sub(_sub, text)
    if saw_zero_noise:
        # a zero-probability NOISE arg in the text is either a true p=0 op
        # (dropped by design) or a nonzero p < 5e-13 that rounded to zero in
        # the 12-decimal format; tell those apart from the in-memory
        # instructions and make the pathological case visible.  (Gated on
        # noise args specifically — annotation args like OBSERVABLE_INCLUDE(0)
        # must not trigger the O(instructions) walk on every compile.)
        def _each_ins(items):
            for item in items:
                if isinstance(item, RepeatBlock):
                    yield from _each_ins(item.body.items)
                else:
                    yield item

        for ins in _each_ins(circuit.items):
            if ins.name in NOISE_NAMES and ins.args and 0 < ins.args[0] < 5e-13:
                warnings.warn(
                    f"noise probability {ins.args[0]!r} formats to 0 in the "
                    "12-decimal circuit text and the op will be dropped "
                    "(compile_circuit docstring, 'Probability precision')",
                    stacklevel=2,
                )
                break
    digest = hashlib.sha256(canon.encode()).hexdigest()
    with _TEMPLATE_CACHE_LOCK:
        template = _TEMPLATE_CACHE.get(digest)
        if template is not None:
            _TEMPLATE_CACHE.move_to_end(digest)
    if template is None:
        template = _compile_circuit_impl(Circuit(canon))
        _freeze_template_arrays(template)
        with _TEMPLATE_CACHE_LOCK:
            _TEMPLATE_CACHE[digest] = template
            if len(_TEMPLATE_CACHE) > _TEMPLATE_CACHE_MAX:
                _TEMPLATE_CACHE.popitem(last=False)
    segs = []
    for seg in template.segments:
        ops = []
        for op in seg.ops:
            if op.kind in ("dep1", "dep2", "perr"):
                idx = int(op.p)
                if op.p != idx or not 1 <= idx <= len(values):
                    # hard error (not assert: silent corruption under -O
                    # would install a wrong probability)
                    raise RuntimeError(
                        "template op carries a non-index probability "
                        f"({op.p!r}) — canonicalization missed a noise "
                        "instruction"
                    )
                op = dataclasses.replace(op, p=values[idx - 1])
            ops.append(op)
        segs.append(dataclasses.replace(seg, ops=ops))
    return dataclasses.replace(template, segments=segs)


def _compile_circuit_impl(circuit: Circuit) -> CompiledCircuit:
    nq = circuit.num_qubits

    # ---- pass 1: resolve record columns for detectors/observables, collect
    # coordinate events, and lower to per-segment proto-op lists
    det_cols: list[list[int]] = []
    obs_cols_by_idx: dict[int, list[int]] = {}
    coord_events: list[tuple] = []
    segments: list[Segment] = []
    meas_count = 0
    det_count = 0

    def walk(items, ops_out: list[Op], seg_rec_base: int):
        nonlocal meas_count, det_count
        for item in items:
            if isinstance(item, RepeatBlock):
                raise ValueError("nested REPEAT blocks are not supported")
            ins = item
            if ins.name == "DETECTOR":
                det_cols.append(
                    sorted(meas_count + t.offset for t in ins.targets)
                )
                if ins.args:
                    coord_events.append(("det", det_count, ins.args))
                det_count += 1
                continue
            if ins.name == "OBSERVABLE_INCLUDE":
                idx = int(ins.args[0]) if ins.args else 0
                obs_cols_by_idx.setdefault(idx, []).extend(
                    meas_count + t.offset for t in ins.targets
                )
                continue
            if ins.name == "SHIFT_COORDS":
                coord_events.append(("shift", tuple(ins.args)))
                continue
            op = _lower_instruction(ins, meas_count - seg_rec_base)
            if ins.name in MEASUREMENT_NAMES:
                meas_count += sum(
                    1 for t in ins.targets if not isinstance(t, RecTarget)
                )
            if op is not None:
                ops_out.extend(op) if isinstance(op, list) else ops_out.append(op)

    pending: list[Op] = []
    pending_rec_offset = 0

    def flush_pending():
        nonlocal pending
        if pending:
            segments.append(
                Segment("block", _fuse(pending), rec_offset=pending_rec_offset)
            )
        pending = []

    for item in circuit.items:
        if isinstance(item, RepeatBlock):
            body = item.body
            if any(isinstance(x, RepeatBlock) for x in body.items):
                # only the outermost repeat is scanned; inner repeats (e.g.
                # the (num_rep-1)-fold sub-round block of the space-time
                # circuit) are unrolled into the scanned body
                flat = Circuit()
                flat.items = list(body.flattened())
                body = flat
            body_meas = body.num_measurements
            body_dets = body.num_detectors
            flush_pending()
            seg_ops: list[Op] = []
            rec_offset = meas_count
            # resolve detector lookbacks against iteration 0; later
            # iterations' columns follow by a uniform +it*body_meas shift
            # (valid for lookbacks into the current or any earlier iteration,
            # e.g. the reference's difference detectors)
            start_meas = meas_count
            start_det = det_count
            body_coord_start = len(coord_events)
            obs_lens_before = {k: len(v) for k, v in obs_cols_by_idx.items()}
            walk(body.items, seg_ops, start_meas)
            first_iter_det = det_cols[start_det:det_count]
            first_iter_coords = coord_events[body_coord_start:]
            first_iter_obs = {
                k: v[obs_lens_before.get(k, 0):]
                for k, v in obs_cols_by_idx.items()
                if len(v) > obs_lens_before.get(k, 0)
            }
            for it in range(1, item.repeat_count):
                shift = it * body_meas
                for cols in first_iter_det:
                    det_cols.append([c + shift for c in cols])
                for k, cols in first_iter_obs.items():
                    obs_cols_by_idx[k].extend(c + shift for c in cols)
                for ev in first_iter_coords:
                    if ev[0] == "det":
                        coord_events.append(
                            ("det", ev[1] + it * body_dets, ev[2])
                        )
                    else:
                        coord_events.append(ev)
            det_count = start_det + item.repeat_count * body_dets
            meas_count = start_meas + item.repeat_count * body_meas
            segments.append(
                Segment(
                    "repeat", _fuse(seg_ops), repeat_count=item.repeat_count,
                    meas_per_iter=body_meas, rec_offset=rec_offset,
                )
            )
        else:
            if not pending:
                pending_rec_offset = meas_count
            walk([item], pending, pending_rec_offset)
    flush_pending()

    # measurement ops inside 'block' segments carry columns relative to the
    # segment; inside 'repeat' segments relative to the iteration (both are
    # shifted by Segment.rec_offset / iteration stride at execution time)

    # ---- assign noise ids
    nid = 0
    for seg in segments:
        for op in seg.ops:
            if op.is_random or op.kind == "measure":
                op.noise_id = nid
                nid += 1

    num_obs = (max(obs_cols_by_idx) + 1) if obs_cols_by_idx else 0
    obs_cols = [sorted(obs_cols_by_idx.get(i, [])) for i in range(num_obs)]

    return CompiledCircuit(
        num_qubits=nq,
        num_measurements=meas_count,
        num_detectors=det_count,
        num_observables=num_obs,
        segments=segments,
        det_cols=det_cols,
        obs_cols=obs_cols,
        coord_events=coord_events,
    )
