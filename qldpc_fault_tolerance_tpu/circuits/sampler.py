"""Vectorized Pauli-frame detector sampler (the TPU replacement for stim's
``compile_detector_sampler``, used at src/Simulators.py:646-651 and
src/Simulators_SpaceTime.py:1025-1029).

A Pauli frame is a pair of bit planes (x, z) of shape (batch, num_qubits)
tracking the difference between the noisy run and a noiseless reference run.
Gates propagate the frame, noise ops XOR PRNG-keyed flips into it, and
measurements copy the relevant plane into a measurement record.  Detector and
observable values are XORs of record columns, evaluated at the end as gathers
/ GF(2) matmuls — so one ``sample`` call is a single fused XLA program:

  * the whole batch advances through each fused op together (scatter/gather
    on the qubit axis — no per-qubit Python, no per-shot work);
  * REPEAT blocks run as ``lax.scan`` over iterations (compile time and HLO
    size independent of the cycle count);
  * per-op randomness comes from ``fold_in``-derived keys, so shots are
    statistically independent by construction (unlike the reference's
    fork-inherited RNG state, SURVEY §2.3).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .ir import Circuit
from .lowering import CompiledCircuit, Op, Segment, compile_circuit

__all__ = ["FrameSampler"]


def _pad_cols(cols_list: list[list[int]], pad: int) -> np.ndarray:
    width = max((len(c) for c in cols_list), default=0)
    out = np.full((len(cols_list), max(width, 1)), pad, dtype=np.int32)
    for i, cols in enumerate(cols_list):
        out[i, : len(cols)] = cols
    return out


def _apply_gate(op: Op, x, z):
    if op.kind == "cx":
        c = jnp.asarray(op.a)
        t = jnp.asarray(op.b)
        x = x.at[:, t].add(x[:, c]) & 1
        z = z.at[:, c].add(z[:, t]) & 1
        return x, z
    if op.kind == "cz":
        a = jnp.asarray(op.a)
        b = jnp.asarray(op.b)
        z = z.at[:, b].add(x[:, a]) & 1
        z = z.at[:, a].add(x[:, b]) & 1
        return x, z
    if op.kind == "h":
        q = jnp.asarray(op.a)
        xq = x[:, q]
        x = x.at[:, q].set(z[:, q])
        z = z.at[:, q].set(xq)
        return x, z
    if op.kind == "reset":
        q = jnp.asarray(op.a)
        return x.at[:, q].set(0), z.at[:, q].set(0)
    raise AssertionError(op.kind)


def _apply_noise(op: Op, key, x, z, p):
    """``p`` is a traced scalar (probs[op.noise_id]) so probability changes
    don't retrace — only the circuit structure is baked into the program."""
    kop = jax.random.fold_in(key, op.noise_id)
    if op.kind == "perr":
        q = jnp.asarray(op.a)
        u = jax.random.uniform(kop, (x.shape[0], len(op.a)))
        flips = (u < p).astype(jnp.uint8)
        if op.fx:
            x = x.at[:, q].add(flips) & 1
        if op.fz:
            z = z.at[:, q].add(flips) & 1
        return x, z
    if op.kind == "dep1":
        q = jnp.asarray(op.a)
        u = jax.random.uniform(kop, (x.shape[0], len(op.a)))
        event = u < p
        comp = jnp.clip((u * (3.0 / p)).astype(jnp.int32), 0, 2)
        fx = (event & (comp <= 1)).astype(jnp.uint8)  # X or Y
        fz = (event & (comp >= 1)).astype(jnp.uint8)  # Y or Z
        x = x.at[:, q].add(fx) & 1
        z = z.at[:, q].add(fz) & 1
        return x, z
    if op.kind == "dep2":
        a = jnp.asarray(op.a)
        b = jnp.asarray(op.b)
        u = jax.random.uniform(kop, (x.shape[0], len(op.a)))
        event = u < p
        comp = jnp.clip((u * (15.0 / p)).astype(jnp.int32), 0, 14) + 1
        p1 = comp >> 2  # first-qubit Pauli in {I,X,Y,Z} = {0,1,2,3}
        p2 = comp & 3
        fxa = (event & ((p1 == 1) | (p1 == 2))).astype(jnp.uint8)
        fza = (event & ((p1 == 2) | (p1 == 3))).astype(jnp.uint8)
        fxb = (event & ((p2 == 1) | (p2 == 2))).astype(jnp.uint8)
        fzb = (event & ((p2 == 2) | (p2 == 3))).astype(jnp.uint8)
        x = x.at[:, a].add(fxa) & 1
        z = z.at[:, a].add(fza) & 1
        x = x.at[:, b].add(fxb) & 1
        z = z.at[:, b].add(fzb) & 1
        return x, z
    raise AssertionError(op.kind)


def _apply_measure(op: Op, key, x, z, buf, rec_cols):
    """Record measurement flips into buf at rec_cols, then collapse/reset."""
    q = jnp.asarray(op.a)
    bits = z[:, q] if op.basis == "x" else x[:, q]
    buf = buf.at[:, jnp.asarray(rec_cols)].set(bits)
    if op.reset_after:
        x = x.at[:, q].set(0)
        z = z.at[:, q].set(0)
    elif op.collapse:
        # measurement collapse: the conjugate frame plane becomes irrelevant;
        # randomize it so later (anti)commuting ops see no spurious signal
        r = jax.random.bernoulli(
            jax.random.fold_in(key, op.noise_id), 0.5, bits.shape
        ).astype(jnp.uint8)
        if op.basis == "x":
            x = x.at[:, q].add(r) & 1
        else:
            z = z.at[:, q].add(r) & 1
    return x, z, buf


class FrameSampler:
    """Compiled detector sampler for one circuit.

    ``sample(key, shots)`` returns ``(detectors, observables)`` as device
    uint8 arrays of shape (shots, num_detectors) / (shots, num_observables).
    ``sample_np`` is the host-array convenience wrapper.
    """

    def __init__(self, circuit: Circuit | CompiledCircuit):
        self.compiled = (
            circuit if isinstance(circuit, CompiledCircuit)
            else compile_circuit(circuit)
        )
        c = self.compiled
        self.num_qubits = c.num_qubits
        self.num_measurements = c.num_measurements
        self.num_detectors = c.num_detectors
        self.num_observables = c.num_observables
        self._det_idx = _pad_cols(c.det_cols, pad=c.num_measurements)
        self._obs_idx = _pad_cols(c.obs_cols, pad=c.num_measurements)
        # noise probabilities as a traced vector indexed by noise_id: circuits
        # that differ only in their error rates (a p-sweep over one memory
        # layout) share one compiled sampler (module cache on structure_key)
        self._structure_key = c.structure_key()
        max_id = max(
            (op.noise_id for seg in c.segments for op in seg.ops
             if op.noise_id >= 0),
            default=-1,
        )
        probs = np.zeros(max(max_id + 1, 1), np.float32)
        for seg in c.segments:
            for op in seg.ops:
                if op.kind in ("dep1", "dep2", "perr"):
                    probs[op.noise_id] = op.p
        self._probs = jnp.asarray(probs)

    def _run_ops(self, ops: list[Op], key, x, z, buf, rec_shift, probs):
        for op in ops:
            if op.kind in ("cx", "cz", "h", "reset"):
                x, z = _apply_gate(op, x, z)
            elif op.kind == "measure":
                x, z, buf = _apply_measure(op, key, x, z, buf, op.rec + rec_shift)
            else:
                x, z = _apply_noise(op, key, x, z, probs[op.noise_id])
        return x, z, buf

    def _sample_impl(self, key, probs, shots: int):
        c = self.compiled
        x = jnp.zeros((shots, self.num_qubits), jnp.uint8)
        z = jnp.zeros((shots, self.num_qubits), jnp.uint8)
        rec = jnp.zeros((shots, self.num_measurements + 1), jnp.uint8)

        for si, seg in enumerate(c.segments):
            kseg = jax.random.fold_in(key, si)
            if seg.kind == "block":
                x, z, rec = self._run_ops(
                    seg.ops, kseg, x, z, rec, seg.rec_offset, probs)
            else:
                per = seg.meas_per_iter

                def body(carry, it, seg: Segment = seg, kseg=kseg, per=per):
                    x, z = carry
                    kit = jax.random.fold_in(kseg, it)
                    buf = jnp.zeros((shots, per + 1), jnp.uint8)
                    # record columns inside the body are iteration-relative;
                    # the stacked scan output is reshaped into the global
                    # record below (iterations are contiguous)
                    xx, zz, buf = self._run_ops(seg.ops, kit, x, z, buf, 0,
                                                probs)
                    return (xx, zz), buf[:, :per]

                (x, z), stacked = jax.lax.scan(
                    body, (x, z), jnp.arange(seg.repeat_count)
                )
                # (iters, shots, per) -> (shots, iters*per)
                stacked = jnp.swapaxes(stacked, 0, 1).reshape(
                    shots, seg.repeat_count * per
                )
                rec = jax.lax.dynamic_update_slice(
                    rec, stacked, (0, seg.rec_offset)
                )

        dets = jnp.zeros((shots, max(self.num_detectors, 1)), jnp.uint8)
        for t in range(self._det_idx.shape[1]):
            dets = dets ^ rec[:, jnp.asarray(self._det_idx[:, t])]
        dets = dets[:, : self.num_detectors]

        obs = jnp.zeros((shots, max(self.num_observables, 1)), jnp.uint8)
        for t in range(self._obs_idx.shape[1]):
            obs = obs ^ rec[:, jnp.asarray(self._obs_idx[:, t])]
        obs = obs[:, : self.num_observables]
        return dets, obs

    # compiled sampler cache: (structure_key, shots) -> jitted (key, probs)
    # closure.  Closing over ONE sampler instance is sound because the
    # structure key digests every array/flag the trace bakes in (only op.p —
    # routed through the traced probs vector — is excluded).
    _CACHE: dict = {}

    def sample(self, key, shots: int):
        fn = FrameSampler._CACHE.get((self._structure_key, shots))
        if fn is None:
            fn = jax.jit(
                functools.partial(self._sample_impl, shots=shots)
            )
            FrameSampler._CACHE[(self._structure_key, shots)] = fn
        return fn(key, self._probs)

    # Samplers hash/compare by circuit structure so they can serve as static
    # jit arguments in the simulators' value-based pipelines: a p-sweep's
    # samplers are interchangeable there (probs arrive as traced arguments).
    def __hash__(self):
        return hash(self._structure_key)

    def __eq__(self, other):
        return (isinstance(other, FrameSampler)
                and self._structure_key == other._structure_key)

    def sample_np(self, seed_or_key, shots: int, append_observables: bool = False):
        """stim-like convenience: host uint8 array, observables appended as
        the trailing columns when requested (the reference always samples with
        ``append_observables=True``, src/Simulators.py:648)."""
        key = (
            jax.random.PRNGKey(seed_or_key)
            if isinstance(seed_or_key, (int, np.integer))
            else seed_or_key
        )
        dets, obs = self.sample(key, shots)
        if append_observables:
            return np.concatenate([np.asarray(dets), np.asarray(obs)], axis=1)
        return np.asarray(dets)
