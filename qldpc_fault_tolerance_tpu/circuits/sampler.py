"""Vectorized Pauli-frame detector sampler (the TPU replacement for stim's
``compile_detector_sampler``, used at src/Simulators.py:646-651 and
src/Simulators_SpaceTime.py:1025-1029).

A Pauli frame is a pair of bit planes (x, z) of shape (batch, num_qubits)
tracking the difference between the noisy run and a noiseless reference run.
Gates propagate the frame, noise ops XOR PRNG-keyed flips into it, and
measurements copy the relevant plane into a measurement record.  Detector and
observable values are XORs of record columns, evaluated at the end as gathers
/ GF(2) matmuls — so one ``sample`` call is a single fused XLA program:

  * the whole batch advances through each fused op together (scatter/gather
    on the qubit axis — no per-qubit Python, no per-shot work);
  * REPEAT blocks run as ``lax.scan`` over iterations (compile time and HLO
    size independent of the cycle count);
  * per-op randomness comes from ``fold_in``-derived keys, so shots are
    statistically independent by construction (unlike the reference's
    fork-inherited RNG state, SURVEY §2.3).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .ir import Circuit
from .lowering import CompiledCircuit, Op, Segment, compile_circuit

__all__ = ["FrameSampler"]


def _pad_cols(cols_list: list[list[int]], pad: int) -> np.ndarray:
    width = max((len(c) for c in cols_list), default=0)
    out = np.full((len(cols_list), max(width, 1)), pad, dtype=np.int32)
    for i, cols in enumerate(cols_list):
        out[i, : len(cols)] = cols
    return out


# ---------------------------------------------------------------------------
# Scatter-free op application.  Index scatters (``x.at[:, idx].add``) lower
# to serial per-index updates on TPU — at hgp_34_n1600 scale (2320 qubits,
# ~200 scatters per sampled batch) they made the sampler ~40 s/batch.  Every
# gate/noise op is instead expressed with per-op STATIC full-width index
# maps and masks (compile-time numpy, memoized): one lane-axis gather plus
# masked XORs over the whole (B, nq) plane, which XLA tiles efficiently.
@functools.lru_cache(maxsize=8192)
def _pairmap(a: tuple, b: tuple, nq: int):
    """Rounds of (src[t]=c / src[c]=t index maps + membership masks).

    The two sides are disjoint (lowering splits cross-side chains), so the
    pairs commute and any decomposition into rounds with per-round-unique
    qubits reproduces the simultaneous (accumulating-scatter) semantics —
    duplicates within a side (one control driving several targets in a
    fused op) land in later rounds."""
    cnt: dict[int, int] = {}
    rounds: dict[int, list[tuple[int, int]]] = {}
    for qa, qb in zip(a, b):
        r = max(cnt.get(qa, 0), cnt.get(qb, 0))
        cnt[qa] = r + 1
        cnt[qb] = r + 1
        rounds.setdefault(r, []).append((qa, qb))
    out = []
    for r in sorted(rounds):
        ra = [p[0] for p in rounds[r]]
        rb = [p[1] for p in rounds[r]]
        ident = np.arange(nq, dtype=np.int32)
        src_t = ident.copy()
        src_t[rb] = ra
        tmask = np.zeros(nq, np.uint8)
        tmask[rb] = 1
        src_c = ident.copy()
        src_c[ra] = rb
        cmask = np.zeros(nq, np.uint8)
        cmask[ra] = 1
        out.append((src_t, tmask, src_c, cmask))
    return tuple(out)


@functools.lru_cache(maxsize=8192)
def _qmask(q: tuple, nq: int):
    assert len(set(q)) == len(q), (
        "noise/gate op with a repeated qubit — lowering must keep "
        "overlapping ops separate (see _mergeable)"
    )
    m = np.zeros(nq, np.uint8)
    m[list(q)] = 1
    return m


@functools.lru_cache(maxsize=8192)
def _pair_expand(a: tuple, b: tuple, nq: int):
    """pairidx[q] = index of q's pair (0 for uninvolved qubits) plus role
    masks — expands per-pair draws to full qubit width with one gather."""
    qs = list(a) + list(b)
    assert len(set(qs)) == len(qs), (
        "dep2 op with a repeated qubit — lowering must keep overlapping "
        "noise ops separate (see _mergeable)"
    )
    pairidx = np.zeros(nq, np.int32)
    rolea = np.zeros(nq, np.uint8)
    roleb = np.zeros(nq, np.uint8)
    for i, (qa, qb) in enumerate(zip(a, b)):
        pairidx[qa] = i
        rolea[qa] = 1
        pairidx[qb] = i
        roleb[qb] = 1
    return pairidx, rolea, roleb


def _apply_gate(op: Op, x, z):
    nq = x.shape[1]
    if op.kind == "cx":
        for src_t, tmask, src_c, cmask in _pairmap(tuple(op.a), tuple(op.b),
                                                   nq):
            x = x ^ (x[:, src_t] & tmask)
            z = z ^ (z[:, src_c] & cmask)
        return x, z
    if op.kind == "cz":
        # z_b ^= x_a and z_a ^= x_b: cross-pair gathers on the x plane only
        # (reads x, writes z — rounds are trivially order-independent)
        for src_t, tmask, src_c, cmask in _pairmap(tuple(op.a), tuple(op.b),
                                                   nq):
            z = z ^ (x[:, src_t] & tmask) ^ (x[:, src_c] & cmask)
        return x, z
    if op.kind == "h":
        m = _qmask(tuple(op.a), nq)
        d = (x ^ z) & m
        return x ^ d, z ^ d
    if op.kind == "reset":
        keep = 1 - _qmask(tuple(op.a), nq)
        return x & keep, z & keep
    raise AssertionError(op.kind)


def _apply_noise(op: Op, key, x, z, p):
    """``p`` is a traced scalar (probs[op.noise_id]) so probability changes
    don't retrace — only the circuit structure is baked into the program."""
    kop = jax.random.fold_in(key, op.noise_id)
    nq = x.shape[1]
    if op.kind == "perr":
        # full-width draw + membership mask (scatter-free; see _apply_gate)
        m = _qmask(tuple(op.a), nq)
        u = jax.random.uniform(kop, (x.shape[0], nq))
        flips = (u < p).astype(jnp.uint8) & m
        if op.fx:
            x = x ^ flips
        if op.fz:
            z = z ^ flips
        return x, z
    if op.kind == "dep1":
        m = _qmask(tuple(op.a), nq)
        u = jax.random.uniform(kop, (x.shape[0], nq))
        event = u < p
        comp = jnp.clip((u * (3.0 / p)).astype(jnp.int32), 0, 2)
        fx = (event & (comp <= 1)).astype(jnp.uint8) & m  # X or Y
        fz = (event & (comp >= 1)).astype(jnp.uint8) & m  # Y or Z
        return x ^ fx, z ^ fz
    if op.kind == "dep2":
        pairidx, rolea, roleb = _pair_expand(tuple(op.a), tuple(op.b), nq)
        u = jax.random.uniform(kop, (x.shape[0], len(op.a)))
        event = u < p
        comp = jnp.clip((u * (15.0 / p)).astype(jnp.int32), 0, 14) + 1
        p1 = comp >> 2  # first-qubit Pauli in {I,X,Y,Z} = {0,1,2,3}
        p2 = comp & 3
        fxa = (event & ((p1 == 1) | (p1 == 2))).astype(jnp.uint8)
        fza = (event & ((p1 == 2) | (p1 == 3))).astype(jnp.uint8)
        fxb = (event & ((p2 == 1) | (p2 == 2))).astype(jnp.uint8)
        fzb = (event & ((p2 == 2) | (p2 == 3))).astype(jnp.uint8)
        # expand per-pair flips to full width with one gather per plane-pair
        fx = (fxa[:, pairidx] & rolea) ^ (fxb[:, pairidx] & roleb)
        fz = (fza[:, pairidx] & rolea) ^ (fzb[:, pairidx] & roleb)
        return x ^ fx, z ^ fz
    raise AssertionError(op.kind)


def _apply_measure(op: Op, key, x, z, buf, rec_cols):
    """Record measurement flips into buf at rec_cols, then collapse/reset."""
    nq = x.shape[1]
    q = jnp.asarray(op.a)
    bits = z[:, q] if op.basis == "x" else x[:, q]
    rc = np.asarray(rec_cols)
    if rc.size and np.all(np.diff(rc) == 1):
        buf = jax.lax.dynamic_update_slice(buf, bits, (0, int(rc[0])))
    else:
        buf = buf.at[:, jnp.asarray(rec_cols)].set(bits)
    if op.reset_after:
        keep = 1 - _qmask(tuple(op.a), nq)
        x = x & keep
        z = z & keep
    elif op.collapse:
        # measurement collapse: the conjugate frame plane becomes irrelevant;
        # randomize it so later (anti)commuting ops see no spurious signal
        m = _qmask(tuple(op.a), nq)
        r = jax.random.bernoulli(
            jax.random.fold_in(key, op.noise_id), 0.5, (x.shape[0], nq)
        ).astype(jnp.uint8) & m
        if op.basis == "x":
            x = x ^ r
        else:
            z = z ^ r
    return x, z, buf


class FrameSampler:
    """Compiled detector sampler for one circuit.

    ``sample(key, shots)`` returns ``(detectors, observables)`` as device
    uint8 arrays of shape (shots, num_detectors) / (shots, num_observables).
    ``sample_np`` is the host-array convenience wrapper.
    """

    def __init__(self, circuit: Circuit | CompiledCircuit):
        self.compiled = (
            circuit if isinstance(circuit, CompiledCircuit)
            else compile_circuit(circuit)
        )
        c = self.compiled
        self.num_qubits = c.num_qubits
        self.num_measurements = c.num_measurements
        self.num_detectors = c.num_detectors
        self.num_observables = c.num_observables
        self._det_idx = _pad_cols(c.det_cols, pad=c.num_measurements)
        self._obs_idx = _pad_cols(c.obs_cols, pad=c.num_measurements)
        # noise probabilities as a traced vector indexed by noise_id: circuits
        # that differ only in their error rates (a p-sweep over one memory
        # layout) share one compiled sampler (module cache on structure_key)
        self._structure_key = c.structure_key()
        max_id = max(
            (op.noise_id for seg in c.segments for op in seg.ops
             if op.noise_id >= 0),
            default=-1,
        )
        probs = np.zeros(max(max_id + 1, 1), np.float32)
        for seg in c.segments:
            for op in seg.ops:
                if op.kind in ("dep1", "dep2", "perr"):
                    probs[op.noise_id] = op.p
        self._probs = jnp.asarray(probs)

    def _run_ops(self, ops: list[Op], key, x, z, buf, rec_shift, probs):
        for op in ops:
            if op.kind in ("cx", "cz", "h", "reset"):
                x, z = _apply_gate(op, x, z)
            elif op.kind == "measure":
                x, z, buf = _apply_measure(op, key, x, z, buf, op.rec + rec_shift)
            else:
                x, z = _apply_noise(op, key, x, z, probs[op.noise_id])
        return x, z, buf

    def _sample_impl(self, key, probs, shots: int):
        c = self.compiled
        x = jnp.zeros((shots, self.num_qubits), jnp.uint8)
        z = jnp.zeros((shots, self.num_qubits), jnp.uint8)
        rec = jnp.zeros((shots, self.num_measurements + 1), jnp.uint8)

        for si, seg in enumerate(c.segments):
            kseg = jax.random.fold_in(key, si)
            if seg.kind == "block":
                x, z, rec = self._run_ops(
                    seg.ops, kseg, x, z, rec, seg.rec_offset, probs)
            else:
                per = seg.meas_per_iter

                def body(carry, it, seg: Segment = seg, kseg=kseg, per=per):
                    x, z = carry
                    kit = jax.random.fold_in(kseg, it)
                    buf = jnp.zeros((shots, per + 1), jnp.uint8)
                    # record columns inside the body are iteration-relative;
                    # the stacked scan output is reshaped into the global
                    # record below (iterations are contiguous)
                    xx, zz, buf = self._run_ops(seg.ops, kit, x, z, buf, 0,
                                                probs)
                    return (xx, zz), buf[:, :per]

                (x, z), stacked = jax.lax.scan(
                    body, (x, z), jnp.arange(seg.repeat_count)
                )
                # (iters, shots, per) -> (shots, iters*per)
                stacked = jnp.swapaxes(stacked, 0, 1).reshape(
                    shots, seg.repeat_count * per
                )
                rec = jax.lax.dynamic_update_slice(
                    rec, stacked, (0, seg.rec_offset)
                )

        dets = jnp.zeros((shots, max(self.num_detectors, 1)), jnp.uint8)
        for t in range(self._det_idx.shape[1]):
            dets = dets ^ rec[:, jnp.asarray(self._det_idx[:, t])]
        dets = dets[:, : self.num_detectors]

        obs = jnp.zeros((shots, max(self.num_observables, 1)), jnp.uint8)
        for t in range(self._obs_idx.shape[1]):
            obs = obs ^ rec[:, jnp.asarray(self._obs_idx[:, t])]
        obs = obs[:, : self.num_observables]
        return dets, obs

    # compiled sampler cache: (structure_key, shots) -> jitted (key, probs)
    # closure.  Closing over ONE sampler instance is sound because the
    # structure key digests every array/flag the trace bakes in (only op.p —
    # routed through the traced probs vector — is excluded).  Bounded so
    # long-lived sweeps over many circuit structures don't pin retired
    # structures' jitted closures (advisor finding, round 2).
    from ..ops.bp import _LruCache as _LRU

    _CACHE = _LRU(maxsize=64)

    def sample(self, key, shots: int):
        fn = FrameSampler._CACHE.get(
            (self._structure_key, shots),
            lambda: jax.jit(functools.partial(self._sample_impl, shots=shots)),
        )
        return fn(key, self._probs)

    # Samplers hash/compare by circuit structure so they can serve as static
    # jit arguments in the simulators' value-based pipelines: a p-sweep's
    # samplers are interchangeable there (probs arrive as traced arguments).
    def __hash__(self):
        return hash(self._structure_key)

    def __eq__(self, other):
        return (isinstance(other, FrameSampler)
                and self._structure_key == other._structure_key)

    def sample_np(self, seed_or_key, shots: int, append_observables: bool = False):
        """stim-like convenience: host uint8 array, observables appended as
        the trailing columns when requested (the reference always samples with
        ``append_observables=True``, src/Simulators.py:648)."""
        key = (
            jax.random.PRNGKey(seed_or_key)
            if isinstance(seed_or_key, (int, np.integer))
            else seed_or_key
        )
        dets, obs = self.sample(key, shots)
        if append_observables:
            return np.concatenate([np.asarray(dets), np.asarray(obs)], axis=1)
        return np.asarray(dets)
