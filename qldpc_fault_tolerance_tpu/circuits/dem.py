"""Detector-error-model derivation and fault-hypergraph extraction.

Replaces ``stim.Circuit.detector_error_model(flatten_loops=True)`` plus the
reference's DEM-text parsers (``GenFaultHyperGraph`` /
``GenCorrecHyperGraph``, src/Simulators_SpaceTime.py:551-668).

Derivation: every noise instruction decomposes into elementary Pauli fault
components (X/Y/Z at p/3 for DEPOLARIZE1, the 15 two-qubit Paulis at p/15 for
DEPOLARIZE2, the literal flip for {X,Y,Z}_ERROR).  Each component is injected
as a deterministic frame flip at its circuit position and propagated through
the Clifford ops to a set of flipped detectors/observables (its *symptom*).
Components are propagated in vectorized host batches over the same lowered op
list the TPU sampler executes — sampling and analysis cannot drift apart.
Components with identical symptoms are merged independently:
p <- p1(1-p2) + p2(1-p1); empty symptoms are dropped.

The text form mirrors stim's flattened DEM layout closely enough for the
reference parsers' assumptions (error lines first; coordinate declarations
``detector(c) D#`` grouped per layer and separated by ``shift_detectors(1) 0``
markers; fixed-point probabilities, src/Simulators_SpaceTime.py:554-575).
"""
from __future__ import annotations

import dataclasses

import numpy as np

from .ir import Circuit, _fmt_arg
from .lowering import compile_circuit

__all__ = [
    "DetectorErrorModel",
    "detector_error_model",
    "GenFaultHyperGraph",
    "GenCorrecHyperGraph",
]


@dataclasses.dataclass
class DetectorErrorModel:
    """errors: list of (probability, detector ids, observable ids)."""

    errors: list
    num_detectors: int
    num_observables: int
    coord_events: list

    def __str__(self):
        lines = []
        for p, dets, obs in self.errors:
            toks = [f"D{d}" for d in dets] + [f"L{o}" for o in obs]
            lines.append(f"error({_fmt_prob(p)}) " + " ".join(toks))
        for ev in self.coord_events:
            if ev[0] == "shift":
                args = ", ".join(_fmt_arg(a) for a in ev[1])
                lines.append(f"shift_detectors({args}) 0")
            else:
                args = ", ".join(_fmt_arg(a) for a in ev[2])
                lines.append(f"detector({args}) D{ev[1]}")
        return "\n".join(lines)


def _fmt_prob(p: float) -> str:
    s = f"{p:.15f}".rstrip("0")
    if s.endswith("."):
        s += "0"
    return s


def _fault_components(op):
    """Yield (x_qubits, z_qubits, prob) elementary components of a noise op."""
    if op.kind == "perr":
        for q in op.a.tolist():
            yield ((q,) if op.fx else ()), ((q,) if op.fz else ()), op.p
    elif op.kind == "dep1":
        for q in op.a.tolist():
            yield (q,), (), op.p / 3  # X
            yield (q,), (q,), op.p / 3  # Y
            yield (), (q,), op.p / 3  # Z
    elif op.kind == "dep2":
        for a, b in zip(op.a.tolist(), op.b.tolist()):
            for comp in range(1, 16):
                p1, p2 = comp >> 2, comp & 3
                xq = tuple(
                    q for q, pl in ((a, p1), (b, p2)) if pl in (1, 2)
                )
                zq = tuple(
                    q for q, pl in ((a, p1), (b, p2)) if pl in (2, 3)
                )
                yield xq, zq, op.p / 15


def _propagate_chunk(ops, faults, nq, num_meas):
    """Propagate a chunk of deterministic faults; returns their measurement
    flip records (F, num_meas) uint8.

    ops: list of (op, unrolled_index); faults: list of
    (position, x_qubits, z_qubits)."""
    F = len(faults)
    fx = np.zeros((F, nq), np.uint8)
    fz = np.zeros((F, nq), np.uint8)
    rec = np.zeros((F, num_meas), np.uint8)
    by_pos: dict[int, list[int]] = {}
    for i, (pos, _, _) in enumerate(faults):
        by_pos.setdefault(pos, []).append(i)

    for op, pos in ops:
        for i in by_pos.get(pos, ()):  # inject at the faulty op's position
            _, xq, zq = faults[i]
            for q in xq:
                fx[i, q] ^= 1
            for q in zq:
                fz[i, q] ^= 1
        k = op.kind
        if k == "cx":
            np.add.at(fx, (slice(None), op.b), fx[:, op.a])
            np.add.at(fz, (slice(None), op.a), fz[:, op.b])
            fx &= 1
            fz &= 1
        elif k == "cz":
            np.add.at(fz, (slice(None), op.b), fx[:, op.a])
            np.add.at(fz, (slice(None), op.a), fx[:, op.b])
            fz &= 1
        elif k == "h":
            tmp = fx[:, op.a].copy()
            fx[:, op.a] = fz[:, op.a]
            fz[:, op.a] = tmp
        elif k == "reset":
            fx[:, op.a] = 0
            fz[:, op.a] = 0
        elif k == "measure":
            rec[:, op.rec] = fz[:, op.a] if op.basis == "x" else fx[:, op.a]
            if op.reset_after:
                fx[:, op.a] = 0
                fz[:, op.a] = 0
            else:
                # projective collapse: a fault component that (anti)commutes
                # trivially with the measured observable acts trivially on the
                # post-measurement state — clear the conjugate plane (the
                # sampler randomizes it instead, which matches in distribution
                # whenever detectors are noiseless-deterministic; DEM
                # derivation, like stim's, requires that determinism)
                if op.basis == "x":
                    fx[:, op.a] = 0
                else:
                    fz[:, op.a] = 0
        # noise ops: nothing to do deterministically
    return rec


def detector_error_model(
    circuit: Circuit, flatten_loops: bool = True, chunk: int = 4096
) -> DetectorErrorModel:
    """Derive the DEM of a noisy circuit (host-side, construction-time).

    ``flatten_loops`` is accepted for stim-signature parity; the model is
    always flattened."""
    del flatten_loops
    c = compile_circuit(circuit)
    ops = list(c.flattened_ops())

    faults = []  # (position, x_qubits, z_qubits, prob)
    for op, pos in ops:
        if op.kind in ("perr", "dep1", "dep2"):
            for xq, zq, p in _fault_components(op):
                faults.append((pos, xq, zq, p))

    det_idx = [np.asarray(cols, np.int64) for cols in c.det_cols]
    obs_idx = [np.asarray(cols, np.int64) for cols in c.obs_cols]

    merged: dict[tuple, float] = {}
    order: list[tuple] = []
    for lo in range(0, len(faults), chunk):
        batch = faults[lo : lo + chunk]
        rec = _propagate_chunk(
            ops, [(f[0], f[1], f[2]) for f in batch], c.num_qubits,
            c.num_measurements,
        )
        # symptom = XOR of record columns per detector/observable
        dets = np.zeros((len(batch), c.num_detectors), np.uint8)
        for d, cols in enumerate(det_idx):
            if len(cols):
                dets[:, d] = rec[:, cols].sum(axis=1) & 1
        obs = np.zeros((len(batch), c.num_observables), np.uint8)
        for o, cols in enumerate(obs_idx):
            if len(cols):
                obs[:, o] = rec[:, cols].sum(axis=1) & 1
        for i, (_, _, _, p) in enumerate(batch):
            sym = (
                tuple(np.flatnonzero(dets[i]).tolist()),
                tuple(np.flatnonzero(obs[i]).tolist()),
            )
            if not sym[0] and not sym[1]:
                continue
            if sym in merged:
                q = merged[sym]
                merged[sym] = q * (1 - p) + p * (1 - q)
            else:
                merged[sym] = p
                order.append(sym)

    errors = [(merged[sym], sym[0], sym[1]) for sym in order]
    return DetectorErrorModel(
        errors=errors,
        num_detectors=c.num_detectors,
        num_observables=c.num_observables,
        coord_events=c.coord_events,
    )


# ---------------------------------------------------------------------------
# Fault-hypergraph extraction (reference GenFaultHyperGraph /
# GenCorrecHyperGraph semantics, src/Simulators_SpaceTime.py:551-668)
# ---------------------------------------------------------------------------

def _parse_dem_text(dem_text: str):
    """Parse DEM text into (errors, detector layers).

    errors: list of (p, det_names list, logical_names list);
    layers: contiguous groups of declared detector names split on
    shift_detectors markers (empty groups dropped)."""
    errors = []
    layers: list[list[str]] = [[]]
    for raw in dem_text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("error"):
            toks = line.split()
            p = float(toks[0][toks[0].index("(") + 1 : toks[0].index(")")])
            dets = [t for t in toks[1:] if t.startswith("D")]
            logs = [t for t in toks[1:] if t.startswith("L")]
            errors.append((p, dets, logs))
        elif line.startswith("shift_detectors"):
            layers.append([])
        elif line.startswith("detector"):
            layers[-1].append(line.split()[-1])
    layers = [g for g in layers if g]
    return errors, layers


def GenFaultHyperGraph(detector_error_model: str, num_rounds: int,
                       num_rep: int, num_logicals: int):
    """Per-layer fault matrices from a DEM (reference
    src/Simulators_SpaceTime.py:551-610).

    Layers are (first window, final); each error is assigned to the first
    layer whose detectors it touches, restricted to that layer's detectors.
    Returns (H_list, L_list, channel_prob_list)."""
    errors, layer_groups = _parse_dem_text(detector_error_model)
    layered = [layer_groups[0], layer_groups[-1]]
    layer_sets = [set(g) for g in layered]

    layered_errors: list[list] = [[], []]
    for p, dets, logs in errors:
        for layer, names in enumerate(layer_sets):
            hit = set(dets) & names
            if hit:
                layered_errors[layer].append((p, hit, set(logs)))
                break

    H_list, L_list, channel_prob_list = [], [], []
    logicals = [f"L{i}" for i in range(num_logicals)]
    for names, errs in zip(layered, layered_errors):
        H = np.zeros((len(names), len(errs)))
        L = np.zeros((num_logicals, len(errs)))
        for j, (_, dets, logs) in enumerate(errs):
            for i, name in enumerate(names):
                if name in dets:
                    H[i, j] = 1
            for i, lg in enumerate(logicals):
                if lg in logs:
                    L[i, j] = 1
        H_list.append(H)
        L_list.append(L)
        channel_prob_list.append([e[0] for e in errs])
    return H_list, L_list, channel_prob_list


def GenCorrecHyperGraph(detector_error_model: str, num_rounds: int,
                        num_rep: int, num_checks: int, num_logicals: int):
    """Space-correction matrix: which next-window first-layer checks each
    first-window fault flips, folded mod 2 over the num_rep+1 detector slices
    (reference src/Simulators_SpaceTime.py:615-668)."""
    errors, layer_groups = _parse_dem_text(detector_error_model)
    layered = [layer_groups[0], layer_groups[-1]]
    layer_sets = [set(g) for g in layered]
    relevant = layered[0] + layered[1]
    relevant_set = set(relevant)

    first_layer_errors = []
    for p, dets, logs in errors:
        for layer, names in enumerate(layer_sets):
            if set(dets) & names:
                if layer == 0:
                    first_layer_errors.append((p, set(dets) & relevant_set))
                break

    H = np.zeros((len(relevant), len(first_layer_errors)))
    for j, (_, dets) in enumerate(first_layer_errors):
        for i, name in enumerate(relevant):
            if name in dets:
                H[i, j] = 1

    H_space_cor = np.zeros((num_checks, len(first_layer_errors)))
    for i in range(num_rep + 1):
        H_space_cor += H[i * num_checks : (i + 1) * num_checks, :]
    return H_space_cor % 2
