"""Single-shot / sustainability study (mirrors the Single-Shot notebook).

1. Code-capacity WER sweep of the hgp_34 family with BP+OSD (ckpt cell 4).
2. Phenomenological WER vs cycle count with FirstMinBP + BPOSD final
   (ckpt cell 9) — the flattening of WER/cycle with growing cycle count is
   the single-shot property.

Run: PYTHONPATH=. python examples/single_shot.py [--quick]
"""
import os
import sys
import time

import numpy as np

from qldpc_fault_tolerance_tpu.codes import load_code
from qldpc_fault_tolerance_tpu.decoders import (
    BPOSD_Decoder_Class,
    FirstMinBP_Decoder_Class,
)
from qldpc_fault_tolerance_tpu.sweep import CodeFamily
from qldpc_fault_tolerance_tpu.utils import SweepCheckpoint, timings

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(quick: bool = True):
    codes = [
        load_code(os.path.join(HERE, "codes_lib_tpu", f"hgp_34_{t}.npz"))
        for t in (["n225", "n625"] if quick else ["n225", "n625", "n1225", "n1600"])
    ]
    print("codes:", [(c.N, c.K) for c in codes])
    samples = 2000 if quick else 10000

    # --- 1. code-capacity WER sweep (BP+OSD) -----------------------------
    family = CodeFamily(
        codes,
        decoder1_class=FirstMinBP_Decoder_Class(5, "minimum_sum", 0.9),
        decoder2_class=BPOSD_Decoder_Class(10, "minimum_sum", 0.625, "osd_e", 10),
        batch_size=2048,
    )
    p_list = [0.02, 0.04, 0.06, 0.08]
    ckpt = SweepCheckpoint(os.path.join(HERE, "examples", ".single_shot.ckpt.jsonl"))
    t0 = time.time()
    wer = family.EvalWER("data", "Total", p_list, samples, if_plot=False,
                         checkpoint=ckpt)
    print(f"data-noise WER array ({time.time()-t0:.1f}s):")
    for c, row in zip(codes, wer):
        print(f"  [[{c.N},{c.K}]]: " + " ".join(f"{w:.2e}" for w in row))

    # --- 2. phenomenological WER vs cycles (single-shot behavior) --------
    t0 = time.time()
    for cycles in ([5, 11] if quick else [5, 11, 17, 23, 29]):
        wer = family.EvalWER("phenl", "Total", [0.02], samples // cycles,
                             num_cycles=cycles, if_plot=False)
        print(f"  phenl p=0.02 cycles={cycles:2d}: WER/cycle = {wer[0,0]:.3e}")
    print(f"sustainability sweep: {time.time()-t0:.1f}s")
    print("stage timings:", timings())


if __name__ == "__main__":
    main(quick="--full" not in sys.argv)
