"""Space-time decoding demo (mirrors SpaceTimeDecodingDemo.ipynb).

Circuit-level noise on the d3 surface code hgp(ring_code(3), ring_code(3)),
sliding-window space-time decoding with num_rep=3 sub-rounds per window over
num_cycles=13, BP window decoder + BP+OSD final decoder
(reference demo cells 1-5).

Run: PYTHONPATH=. python examples/spacetime_demo.py
"""
import time

import jax
import numpy as np

from qldpc_fault_tolerance_tpu.codes import hgp, ring_code
from qldpc_fault_tolerance_tpu.decoders import (
    ST_BP_Decoder_Circuit_Class,
    ST_BPOSD_Decoder_Circuit_Class,
)
from qldpc_fault_tolerance_tpu.sweep import CodeFamily_SpaceTime


def main():
    code = hgp(ring_code(3), ring_code(3))
    print(f"surface code d3: [[{code.N},{code.K}]]")

    family = CodeFamily_SpaceTime(
        [code],
        decoder1_class=ST_BP_Decoder_Circuit_Class(1, "minimum_sum", 0.625),
        decoder2_class=ST_BPOSD_Decoder_Circuit_Class(
            1, "minimum_sum", 0.625, "osd_e", 10),
        batch_size=1024,
    )
    # demo cell 2 error params: CX depolarizing noise only
    circuit_error_params = {
        "p_i": 0, "p_state_p": 0, "p_m": 0, "p_CX": 1, "p_idling_gate": 0,
    }
    p_list = [0.002, 0.004, 0.008]
    t0 = time.time()
    wer_list, p_adapt = family.EvalWER(
        "circuit", "Z", p_list, num_samples=4096, num_cycles=13, num_rep=3,
        circuit_error_params=circuit_error_params, if_plot=False,
    )
    print(f"p grid:     {list(p_adapt[0])}")
    print(f"WER/cycle:  {[f'{w:.3e}' for w in wer_list[0]]}")
    print(f"elapsed:    {time.time() - t0:.1f}s")


if __name__ == "__main__":
    main()
