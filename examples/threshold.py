"""Threshold estimation (mirrors the Threshold notebook).

Phenomenological and circuit-level threshold fits for the hgp_34 family:
decoder 1 = plain BP over the extended [H|I] matrix (N/30 iterations),
decoder 2 = BP+OSD (N/10 iterations) — Threshold ckpt cells 2-4.

Run: PYTHONPATH=. python examples/threshold.py [--full]
"""
import os
import sys
import time

from qldpc_fault_tolerance_tpu.codes import load_code
from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder_Class, BP_Decoder_Class
from qldpc_fault_tolerance_tpu.sweep import CodeFamily

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(quick: bool = True):
    tags = ["n225", "n625"] if quick else ["n225", "n625", "n1225"]
    codes = [
        load_code(os.path.join(HERE, "codes_lib_tpu", f"hgp_34_{t}.npz"))
        for t in tags
    ]
    print("codes:", [(c.N, c.K) for c in codes])
    samples = 2000 if quick else 12000

    family = CodeFamily(
        codes,
        decoder1_class=BP_Decoder_Class(30, "minimum_sum", 0.625),
        decoder2_class=BPOSD_Decoder_Class(10, "minimum_sum", 0.625, "osd_e", 10),
        batch_size=2048,
    )

    # phenomenological threshold at a fixed cycle count (ckpt cell 12 ran
    # cycles in {6..30}; published p_c at 6 cycles: 0.0900)
    t0 = time.time()
    pc = family.EvalThreshold(
        "phenl", "Total", "extrapolation", est_threshold=0.07,
        num_samples=samples, num_cycles=5, if_plot=False,
    )
    print(f"phenl threshold (5 cycles): p_c = {pc:.4f}  ({time.time()-t0:.1f}s)")

    # circuit-level threshold (ckpt cell 29: analytic decoder priors
    # p_data = 3*6*(8/15) p, p_synd = 7*(8/15) p; published p_c at 3 cycles:
    # 0.0392)
    circuit_error_params = {
        "p_i": 0, "p_state_p": 0, "p_m": 0, "p_CX": 1, "p_idling_gate": 0,
    }
    t0 = time.time()
    pc = family.EvalThreshold(
        "circuit", "Z", "extrapolation", est_threshold=0.01,
        num_samples=samples, num_cycles=3,
        data_synd_noise_ratio=3 * 6 * (8 / 15) / (7 * 8 / 15),
        circuit_error_params=circuit_error_params, if_plot=False,
    )
    print(f"circuit threshold (3 cycles): p_c = {pc:.4f}  ({time.time()-t0:.1f}s)")


if __name__ == "__main__":
    main(quick="--full" not in sys.argv)
