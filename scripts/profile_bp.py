"""Thin wrapper: component-level BP pipeline timing moved to
``scripts/perf_report.py bp`` (the ISSUE-6 performance-attribution CLI).

Usage: python scripts/profile_bp.py [batch]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from perf_report import cmd_bp  # noqa: E402


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    return cmd_bp(batch)


if __name__ == "__main__":
    raise SystemExit(main())
