"""Component-level timing of the bench pipeline on the live chip.

Usage: python scripts/profile_bp.py [batch]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from qldpc_fault_tolerance_tpu.codes import load_code
from qldpc_fault_tolerance_tpu.noise import depolarizing_xz
from qldpc_fault_tolerance_tpu.ops import bp
from qldpc_fault_tolerance_tpu.ops.linalg import gf2_matmul


def timeit(fn, *args, reps=20, **kw):
    """Steady-state: launch ``reps`` async dispatches, sync once (the tunneled
    chip has ~100ms host<->device latency, so per-dispatch blocking times the
    tunnel, not the compute)."""
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps, out


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = load_code(os.path.join(here, "codes_lib_tpu", "hgp_34_n625.npz"))
    p = 0.01
    graph = bp.build_tanner_graph(code.hx)
    llr0 = bp.llr_from_probs(np.full(code.N, p))
    hx_t = jnp.asarray(code.hx.T)

    key = jax.random.PRNGKey(0)

    @jax.jit
    def sample(key):
        ex, ez = depolarizing_xz(key, (batch, code.N), (p / 3, p / 3, p / 3))
        return ez, gf2_matmul(ez, hx_t)

    t_sample, (ez, synd) = timeit(sample, key)
    print(f"sample+syndrome: {t_sample*1e3:.2f} ms  ({batch/t_sample:,.0f}/s)")

    frac = []
    for hi in (2, 3, 5):
        r = bp.bp_decode(graph, synd, llr0, max_iter=hi)
        frac.append((hi, 1 - float(r.converged.mean())))
    print("unconverged frac after iters:", frac)
    r50 = bp.bp_decode(graph, synd, llr0, max_iter=50)
    print("unconverged frac after 50:", 1 - float(r50.converged.mean()))

    for name, fn in [
        ("bp_decode(50, early_stop)", lambda s: bp.bp_decode(graph, s, llr0, max_iter=50)),
        ("bp_decode(50, no early)", lambda s: bp.bp_decode(graph, s, llr0, max_iter=50, early_stop=False)),
        ("bp_decode(3)", lambda s: bp.bp_decode(graph, s, llr0, max_iter=3)),
        ("two_phase(3,B/16)", lambda s: bp.bp_decode_two_phase(graph, s, llr0, max_iter=50)),
        ("two_phase(5,B/32)", lambda s: bp.bp_decode_two_phase(graph, s, llr0, max_iter=50, head_iters=5, tail_capacity=batch // 32)),
    ]:
        t, _ = timeit(fn, synd)
        print(f"{name}: {t*1e3:.2f} ms  ({batch/t:,.0f} dec/s)")


if __name__ == "__main__":
    main()
