"""Unified performance-attribution CLI (utils.profiling front end).

Consolidates the ad-hoc round-2..4 profilers — scripts/profile_bp.py,
scripts/profile_bposd.py, scripts/tpu_timing.py — onto the ISSUE-6
profiling subsystem.  Subcommands:

    python scripts/perf_report.py bp [--batch 8192]
        Component-level timing of the bench BP pipeline (sample+syndrome,
        bp_decode variants, two-phase) — the old profile_bp report.
    python scripts/perf_report.py bposd [--batch 2048]
        Stage-split BP+OSD timing at the bench bposd operating point
        (BP alone, device OSD-0/OSD-E, full decode) — old profile_bposd.
    python scripts/perf_report.py costs [--batch 2048 --batches 8]
        XLA cost-model capture of the megabatch program: measured
        flops/bytes/peak per program + derived mfu/hbm_util at the
        measured rate.
    python scripts/perf_report.py waterfall [--batch 2048 --shots 16384]
        Device-time waterfall of one WordErrorRate run: per-stage device
        times (sample→syndrome / BP / residual), dispatch-launch /
        device / host-sync / gap decomposition, dispatch_gap_fraction.
    python scripts/perf_report.py calibration
        Summary of the VMEM calibration table the Pallas gates consume
        (regenerate with scripts/vmem_calibrate.py).

The slope-based tunnel-safe timer lives at
``qldpc_fault_tolerance_tpu.utils.profiling.per_call_seconds`` (moved from
scripts/tpu_timing.py, which is now a shim).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench_code(small: bool = False):
    if small:
        from qldpc_fault_tolerance_tpu.codes import hgp, rep_code

        return hgp(rep_code(3), rep_code(3), name="hgp_rep3")
    sys.path.insert(0, REPO)
    import bench

    return bench._bench_code()


def _make_bp_sim(code, batch: int, batches: int):
    import numpy as np

    from qldpc_fault_tolerance_tpu.decoders import BPDecoder
    from qldpc_fault_tolerance_tpu.sim.data_error import (
        CodeSimulator_DataError)

    p = 0.01
    dec_x = BPDecoder(code.hz, np.full(code.N, p), max_iter=50)
    dec_z = BPDecoder(code.hx, np.full(code.N, p), max_iter=50)
    return CodeSimulator_DataError(
        code=code, decoder_x=dec_x, decoder_z=dec_z,
        pauli_error_probs=[p / 3] * 3, batch_size=batch, seed=0,
        scan_chunk=batches)


# ---------------------------------------------------------------------------
# bp: component-level timing (the old scripts/profile_bp.py report)
# ---------------------------------------------------------------------------
def cmd_bp(batch: int = 8192) -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from qldpc_fault_tolerance_tpu.noise import depolarizing_xz
    from qldpc_fault_tolerance_tpu.ops import bp
    from qldpc_fault_tolerance_tpu.ops.linalg import gf2_matmul
    from qldpc_fault_tolerance_tpu.utils import profiling

    code = _bench_code()
    p = 0.01
    graph = bp.build_tanner_graph(code.hx)
    llr0 = bp.llr_from_probs(np.full(code.N, p))
    hx_t = jnp.asarray(code.hx.T)
    key = jax.random.PRNGKey(0)

    @jax.jit
    def sample(key):
        ex, ez = depolarizing_xz(key, (batch, code.N), (p / 3,) * 3)
        return ez, gf2_matmul(ez, hx_t)

    # timeit_async = the old profile_bp protocol: reps async dispatches,
    # ONE sync — per-rep blocking would time the tunnel, not the compute
    t_sample, (ez, synd) = profiling.timeit_async(sample, key)
    print(f"sample+syndrome: {t_sample*1e3:.2f} ms  "
          f"({batch/t_sample:,.0f}/s)")

    frac = []
    for hi in (2, 3, 5):
        r = bp.bp_decode(graph, synd, llr0, max_iter=hi)
        frac.append((hi, 1 - float(r.converged.mean())))
    print("unconverged frac after iters:", frac)
    r50 = bp.bp_decode(graph, synd, llr0, max_iter=50)
    print("unconverged frac after 50:", 1 - float(r50.converged.mean()))

    for name, fn in [
        ("bp_decode(50, early_stop)",
         lambda s: bp.bp_decode(graph, s, llr0, max_iter=50)),
        ("bp_decode(50, no early)",
         lambda s: bp.bp_decode(graph, s, llr0, max_iter=50,
                                early_stop=False)),
        ("bp_decode(3)", lambda s: bp.bp_decode(graph, s, llr0, max_iter=3)),
        ("two_phase(3,B/16)",
         lambda s: bp.bp_decode_two_phase(graph, s, llr0, max_iter=50)),
        ("two_phase(5,B/32)",
         lambda s: bp.bp_decode_two_phase(graph, s, llr0, max_iter=50,
                                          head_iters=5,
                                          tail_capacity=batch // 32)),
    ]:
        t, _ = profiling.timeit_async(fn, synd)
        print(f"{name}: {t*1e3:.2f} ms  ({batch/t:,.0f} dec/s)")
    return 0


# ---------------------------------------------------------------------------
# bposd: stage-split BP+OSD (the old scripts/profile_bposd.py report)
# ---------------------------------------------------------------------------
def cmd_bposd(batch: int = 2048) -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder
    from qldpc_fault_tolerance_tpu.decoders.bp_decoders import decode_device
    from qldpc_fault_tolerance_tpu.ops import bp
    from qldpc_fault_tolerance_tpu.ops.osd_device import (
        build_osd_plan,
        osd_decode_values,
    )
    from qldpc_fault_tolerance_tpu.utils import profiling

    code = _bench_code()
    p = 0.05
    two_thirds = 2 * p / 3
    mi = int(code.N / 10)
    dec = BPOSD_Decoder(code.hx, np.full(code.N, two_thirds), max_iter=mi,
                        osd_method="osd_e", osd_order=10)
    key = jax.random.PRNGKey(0)
    err = jax.random.bernoulli(key, two_thirds, (batch, code.N))
    synd = ((err.astype(jnp.uint8) @ jnp.asarray(code.hx.T)) % 2).astype(
        jnp.uint8)

    graph = bp.build_tanner_graph(code.hx)
    llr0 = bp.llr_from_probs(np.full(code.N, two_thirds))

    @jax.jit
    def bp_only(synd):
        return bp.bp_decode(graph, synd, llr0, max_iter=mi)

    t_bp, res = profiling.timeit_async(bp_only, synd, reps=10)
    conv = np.asarray(res.converged)
    print(f"batch={batch}  BP({mi} iters): {t_bp * 1e3:.1f} ms  "
          f"converged={conv.mean():.3f}  n_bad={int((~conv).sum())}")

    plan = build_osd_plan(code.hx, np.full(code.N, two_thirds))
    llrs = jnp.asarray(res.posterior_llr)
    for sub in sorted({256, 512, batch}):
        if sub > batch:
            continue
        s_sub, l_sub = synd[:sub], llrs[:sub]
        for order, label in ((0, "OSD-0 (elim+solve)"),
                             (10, "OSD-E order 10")):
            fn = jax.jit(lambda s, l, o=order: osd_decode_values(
                (plan.n, plan.rank, o, 256,
                 os.environ.get("QLDPC_OSD_ELIM", "pallas")),
                plan.packed, plan.cost, s, l))
            t, _ = profiling.timeit_async(fn, s_sub, l_sub, reps=10)
            print(f"  osd batch={sub:5d} {label:18s}: {t * 1e3:7.1f} ms  "
                  f"({sub / t:8.0f} shots/s)")

    @jax.jit
    def full(synd):
        return decode_device(dec.device_static, dec.device_state, synd)

    t_full, _ = profiling.timeit_async(full, synd, reps=10)
    print(f"full BPOSD decode_device: {t_full * 1e3:.1f} ms  "
          f"({batch / t_full:.0f} shots/s)")
    return 0


# ---------------------------------------------------------------------------
# costs: cost-model capture + derived utilization
# ---------------------------------------------------------------------------
def cmd_costs(batch: int = 2048, batches: int = 8,
              small: bool = False) -> int:
    import jax

    from qldpc_fault_tolerance_tpu.utils import profiling

    code = _bench_code(small)
    sim = _make_bp_sim(code, batch, batches)
    shots = batch * batches
    key = jax.random.PRNGKey(123)
    with profiling.profile_session():
        sim.WordErrorRate(shots, key=key)  # warm + capture
        t0 = time.perf_counter()
        sim.WordErrorRate(shots, key=key)
        rate = shots / (time.perf_counter() - t0)
        costs = profiling.program_costs()
    print(f"rate: {rate:,.1f} shots/s  ({code.name}, batch {batch} x "
          f"{batches})")
    for label, c in costs.items():
        util = profiling.derive_utilization(c, batch, rate)
        print(f"-- {label} (backend {c['backend']}) --")
        for k in ("flops", "bytes_accessed", "argument_bytes",
                  "output_bytes", "temp_bytes", "peak_bytes"):
            print(f"  {k:<18}{c[k]:,.0f}")
        for k, v in util.items():
            print(f"  {k:<18}{v}")
    print("note: XLA cost model counts loop bodies once -> per-shot "
          "figures normalize by ONE scan-body batch")
    return 0


# ---------------------------------------------------------------------------
# waterfall: run decomposition + per-stage device times
# ---------------------------------------------------------------------------
def cmd_waterfall(batch: int = 2048, shots: int = 16384,
                  small: bool = False) -> int:
    import jax

    from qldpc_fault_tolerance_tpu.utils import profiling

    sys.path.insert(0, REPO)
    import bench

    code = _bench_code(small)
    batches = max(1, shots // batch)
    sim = _make_bp_sim(code, batch, batches)
    shots = batch * batches
    key = jax.random.PRNGKey(123)
    with profiling.profile_session():
        # warm INSIDE the session: compiles + the one-time cost capture
        # happen here, not in the timed waterfall run
        sim.WordErrorRate(shots, key=key)
        stages = bench._device_stage_times(sim, jax.random.fold_in(key, 97))
        with profiling.deep_timing(), \
                profiling.engine_scope("perf_report") as acct:
            t0 = time.perf_counter()
            sim.WordErrorRate(shots, key=key)
            wf = acct.waterfall(time.perf_counter() - t0)
    total = sum(stages.values()) or 1.0
    print(f"run: {shots} shots, wall {wf['wall_s']}s, "
          f"{wf['n_dispatches']} dispatches, {wf['n_syncs']} syncs")
    print("-- per-batch device stages --")
    for name, secs in stages.items():
        print(f"  {name:<18}{secs*1e3:9.2f} ms  ({secs/total:6.1%})")
    print("-- run decomposition --")
    for name, secs in wf["stages"].items():
        print(f"  {name:<18}{secs:9.4f} s")
    print(f"dispatch_gap_fraction: {wf['dispatch_gap_fraction']}")
    return 0


# ---------------------------------------------------------------------------
# calibration: VMEM table summary
# ---------------------------------------------------------------------------
def cmd_calibration() -> int:
    from qldpc_fault_tolerance_tpu.utils import profiling

    path = profiling.vmem_table_path()
    table = profiling.vmem_table(refresh=True)
    entries = table.get("entries", [])
    print(f"table: {path}")
    if not entries:
        print("  (missing or empty — run scripts/vmem_calibrate.py)")
        return 1
    print(f"  schema {table.get('schema')}  backend "
          f"{table.get('backend')}  measured={table.get('measured')}  "
          f"generated {table.get('generated_at')}")
    print(f"  ratios: {json.dumps(table.get('ratios', {}))}")
    print(f"  gates:  {json.dumps(table.get('gates', {}))}")
    for e in entries:
        shape = ", ".join(f"{k}={e[k]}" for k in ("rw", "m", "n", "mx", "mz")
                          if k in e)
        block = e.get("max_block_b", e.get("max_block_w"))
        extra = ""
        if e.get("per_shot_bytes"):
            extra = (f"  per_shot={e['per_shot_bytes']:,.0f}B "
                     f"(x{e.get('ratio_vs_analytic', '?')} analytic)")
        print(f"  {e['kernel']:<16} {e.get('code', '?'):<14} {shape:<28} "
              f"max_block={block}{extra}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_bp = sub.add_parser("bp")
    p_bp.add_argument("--batch", type=int, default=8192)
    p_bo = sub.add_parser("bposd")
    p_bo.add_argument("--batch", type=int, default=2048)
    p_c = sub.add_parser("costs")
    p_c.add_argument("--batch", type=int, default=2048)
    p_c.add_argument("--batches", type=int, default=8)
    p_c.add_argument("--small", action="store_true",
                     help="tiny hgp_rep3 code (CI smoke)")
    p_w = sub.add_parser("waterfall")
    p_w.add_argument("--batch", type=int, default=2048)
    p_w.add_argument("--shots", type=int, default=16384)
    p_w.add_argument("--small", action="store_true")
    sub.add_parser("calibration")
    args = ap.parse_args(argv)

    if args.cmd == "bp":
        return cmd_bp(args.batch)
    if args.cmd == "bposd":
        return cmd_bposd(args.batch)
    if args.cmd == "costs":
        return cmd_costs(args.batch, args.batches, args.small)
    if args.cmd == "waterfall":
        return cmd_waterfall(args.batch, args.shots, args.small)
    return cmd_calibration()


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:  # e.g. `... | head` — not an error
        raise SystemExit(0)
