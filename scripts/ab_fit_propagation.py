"""Propagate ab_iteration.py's per-code WER ratios through the notebook fit.

For each decoder-variant hypothesis ("the reference's ldpc binaries behave
like our arm X"), scale the recorded round-3 toric_circuit WER grids by the
measured per-code ratio WER(arm)/WER(base) and refit with the notebook's
two-stage ThresholdEst.  If a hypothesis lands the fitted p_c on the
published value, it quantitatively explains the offset; if none reaches it,
the bound ("no tested decoder variant moves p_c by more than Y%") is the
deliverable.

Usage: python scripts/ab_fit_propagation.py [--ab AB_ITERATION.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from parity import EXPERIMENTS, notebook_threshold_est  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ab", default=os.path.join(REPO, "AB_ITERATION.json"))
    ap.add_argument("--cycles", type=int, nargs="*", default=[20, 25, 30])
    args = ap.parse_args()
    ab = json.load(open(args.ab))
    arms = list(ab["results"][0]["failures"])
    ratios = {}
    for arm in arms:
        ratios[arm] = [r["failures"][arm] / max(r["failures"]["base"], 1)
                       for r in ab["results"]]
    print("measured per-code WER ratios (d5, d9, d13):")
    for arm, rr in ratios.items():
        print(f"  {arm:7s}: {[f'{x:.3f}' for x in rr]}")

    recs = [json.loads(l) for l in open(os.path.join(REPO,
                                                     "PARITY_results.jsonl"))]
    published = EXPERIMENTS["toric_circuit"]["published"]
    for cycles in args.cycles:
        rows = [r for r in recs
                if r["experiment"] == "toric_circuit"
                and r["cycles"] == cycles
                and r.get("circuit_type") in (None, "coloration")
                # exclude decoder-variant A/B and 4-member d_eff rows (same
                # filter as parity_report.py) — only msf-0.625 3-member rows
                # are valid baselines to perturb
                and r.get("msf") in (None, 0.625)
                and not r.get("members")]
        if not rows:
            continue
        pcs = {arm: [] for arm in arms}
        for r in rows:
            wer = np.array(r["wer"])
            for arm in arms:
                w2 = wer * np.array(ratios[arm])[:, None]
                try:
                    pc, _, _ = notebook_threshold_est(r["p_list"], w2)
                except RuntimeError:
                    continue
                pcs[arm].append(pc)
        print(f"\ncycles={cycles} (published p_c = {published[cycles]}):")
        for arm in arms:
            if pcs[arm]:
                mu = float(np.mean(pcs[arm]))
                print(f"  arm {arm:7s}: mean p_c {mu:.5f} over "
                      f"{len(pcs[arm])} seeds  "
                      f"(vs published {mu / published[cycles] - 1:+.1%})")


if __name__ == "__main__":
    main()
