"""Bench regression ledger: normalize the BENCH_r*.json history, render the
round-to-round trajectory with tolerance bands, and gate regressions.

    python scripts/bench_compare.py                    # trajectory table
    python scripts/bench_compare.py --json             # machine-readable
    python scripts/bench_compare.py --gate             # exit 1 on regression
    python scripts/bench_compare.py --gate --tolerance 5 BENCH_r0*.json

Three artifact schemas are accepted per round (the ledger spans them):

  * the driver wrapper the r01–r05 history uses:
    ``{"n": <round>, "cmd": ..., "rc": ..., "tail": ..., "parsed": {...}}``;
  * schema-2 ledger rounds: ``{"schema": 2, "round": <n>, "result": {...}}``
    (what a future bench harness should write);
  * a bare bench.py result line: ``{"metric": ..., "value": ...}`` (round
    inferred from the filename's ``r<NN>``).

The gate compares CONSECUTIVE rounds on the headline ``value`` plus any
stage-rate fields present in both rounds (``GATED_FIELDS`` — the
CPU-measurable sample→syndrome substrate rates, the whole-grid sweep
speedup, and the decode-service QPS companions ``shots_per_s`` /
``p99_ms``), and fails when any drops more than ``--tolerance`` percent.
Higher-is-better is assumed for shots/s metrics; wall-clock metrics
(``unit == "s"``) and latency fields (``LOWER_IS_BETTER_FIELDS``, e.g. the
serve round's tail latency) gate on INCREASES instead.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LEDGER_SCHEMA = 2

# dotted paths into the normalized fields dict, gated when present in BOTH
# rounds of a consecutive pair (the headline "value" is always gated)
GATED_FIELDS = (
    "sample_synd_shots_per_s.dense",
    "sample_synd_shots_per_s.packed",
    "sample_synd_shots_per_s.fused",
    "fused_speedup_vs_serial",
    # decode-service rounds (bench.py serve): aggregate decoded shots/s
    # rides alongside the QPS headline, and the tail-latency SLO gates on
    # INCREASES (LOWER_IS_BETTER_FIELDS)
    "shots_per_s",
    "p99_ms",
    # BP kernel v2 (ISSUE 9): the kernel A/B arms and the MEASURED
    # utilization must not regress once recorded.  The measured keys gate
    # under their cost_model.* names — the legacy top-level "hbm_util" was
    # a hand model whose r03->r04 roofline correction (0.257 -> 0.012) is
    # a semantic change, not a regression, so it stays ungated; r01-r05
    # lack every key below and the checked-in history gates unchanged.
    "kernel_ab.v1_shots_per_s",
    "kernel_ab.v2_shots_per_s",
    "quant_ab.int8_shots_per_s",
    "cost_model.mfu",
    "cost_model.hbm_util",
    # rare-event estimation rounds (bench.py rare, ISSUE 10): the
    # variance-reduction factors and the weighted arm's throughput must
    # not regress once recorded; r01-r05 lack these keys so the checked-in
    # history gates unchanged
    "vrf_equal_shots",
    "vrf_fixed_wallclock",
    "weighted_shots_per_s",
    # request tracing (bench.py serve tracing A/B, ISSUE 11): the TRACED
    # arm's throughput is the robust regression signal (overhead_pct sits
    # near zero where percent-change gating is meaningless); its tail
    # latency gates on increases.  Rounds before r06 lack the keys, so
    # the checked-in history gates unchanged.
    "tracing_ab.traced_shots_per_s",
    "tracing_ab.traced_p99_ms",
    # chaos-hardened serving (bench.py serve journal A/B + bench.py
    # chaos, ISSUE 14): the JOURNALED arm's throughput is the robust
    # regression signal for the idempotency journal's steady-state cost;
    # chaos rounds gate their under-fault QPS (the recovery headline is
    # the round's "value", unit "s" — gated lower-is-better by the
    # standard wall-clock rule).  Rounds before r06 lack the keys, so
    # the checked-in history gates unchanged.
    "journal_ab.journaled_shots_per_s",
    "chaos_qps",
    # device-resident BPOSD (bench.py bposd, ISSUE 13): the end-to-end
    # BPOSD rate and both arms of the device-vs-host OSD A/B gate as rate
    # fields; host round-trips gate on INCREASES (a reappearing host sync
    # is the regression — 0-valued rounds skip percent gating, so the
    # first nonzero round is what trips it).  Rounds before r06 lack every
    # key, so the checked-in r01-r05 history gates unchanged.
    "bposd.shots_per_s",
    "osd_ab.device_shots_per_s",
    "osd_ab.host_shots_per_s",
    "bposd.host_round_trips",
    # device OSD-CS (ISSUE 19): the batched combination-sweep arm gates as
    # a rate; cs_host_round_trips gates on 0 -> nonzero like the osd_e
    # counter (a reappearing host round-trip IS the regression)
    "cs_ab.device_cs_shots_per_s",
    "bposd.cs_host_round_trips",
    # serving scaling half (bench.py serve, ISSUE 15): the packed wire's
    # bytes/request gates on INCREASES (a layout/header regression shows
    # up as more bytes on the wire), the cross-session fused dispatch
    # A/B's fused arm gates as a rate alongside the new fused+packed
    # headline ("value").  Rounds before r06 lack the keys, so the
    # checked-in r01-r05 history gates unchanged.
    "wire_ab.packed_bytes_per_req",
    "fused_ab.fused_req_per_s",
    # streaming decode (bench.py stream, ISSUE 16): sustained committed
    # cycles/s per stream gates as a rate; the p99 commit latency gates on
    # INCREASES; the windowed-vs-whole A/B's compute-per-committed-cycle
    # advantage must not erode (the >=5x acceptance floor is enforced by
    # the bench round's own gates block — here it gates round-to-round).
    # Rounds before r16 lack the keys, so the checked-in history gates
    # unchanged.
    "stream.cycles_per_s",
    "stream.ab_compute_per_cycle_ratio",
    "stream.p99_commit_ms",
    # fleet observability plane (bench.py BP timeseries A/B, ISSUE 17):
    # the SCRAPER-ON arm's throughput is the robust regression signal for
    # the retention+alerting cost (its overhead_pct sits near zero where
    # percent-change gating is meaningless — same reasoning as the tracing
    # arm).  Rounds before r17 lack the key, so the checked-in history
    # gates unchanged.
    "timeseries_ab.scraper_on_shots_per_s",
    # multi-host serving fabric (bench.py fleet, ISSUE 18): the fleet
    # storm's through-kill request rate gates as a rate (also the round's
    # "value" headline); the handoff wall clock (gate -> journal flush ->
    # adopt -> reopen) gates on INCREASES.  Rounds before r18 lack the
    # keys, so the checked-in history gates unchanged.
    "fleet.req_per_s",
    "fleet.handoff_p99_ms",
    # persistent AOT program cache (bench.py coldstart, ISSUE 20): the
    # warm time-to-first-decode and warm handoff tail gate on INCREASES
    # (a cache regression shows up as the warm path re-compiling); the
    # cache hit rate gates as a rate.  Rounds before r20 lack the keys,
    # so the checked-in history gates unchanged.
    "coldstart.ttfd_s",
    "coldstart.progcache_hit_rate",
    "coldstart.handoff_warm_p99_ms",
)

# gated fields where a RISE is the regression (latencies, host round-trips)
LOWER_IS_BETTER_FIELDS = frozenset({"p99_ms", "tracing_ab.traced_p99_ms",
                                    "bposd.host_round_trips",
                                    "bposd.cs_host_round_trips",
                                    "wire_ab.packed_bytes_per_req",
                                    "stream.p99_commit_ms",
                                    "fleet.handoff_p99_ms",
                                    "coldstart.ttfd_s",
                                    "coldstart.handoff_warm_p99_ms"})


def _dig(d: dict, dotted: str):
    cur = d
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur if isinstance(cur, (int, float)) else None


def normalize_round(obj: dict, fallback_round=None) -> dict | None:
    """One artifact -> ``{"round", "schema", "metric", "value", "unit",
    "fields"}`` or None when the object isn't a bench round."""
    if not isinstance(obj, dict):
        return None
    if obj.get("schema") == LEDGER_SCHEMA and isinstance(
            obj.get("result"), dict):
        result = obj["result"]
        rnd = obj.get("round", fallback_round)
        schema = LEDGER_SCHEMA
    elif isinstance(obj.get("parsed"), dict):  # legacy driver wrapper
        result = obj["parsed"]
        rnd = obj.get("n", fallback_round)
        schema = 1
    elif "value" in obj and "metric" in obj:   # bare bench.py line
        result = obj
        rnd = fallback_round
        schema = 0
    else:
        return None
    if not isinstance(result.get("value"), (int, float)):
        return None
    return {
        "round": rnd,
        "schema": schema,
        "metric": result.get("metric", "?"),
        "value": float(result["value"]),
        "unit": result.get("unit", ""),
        "fields": result,
    }


def _round_from_name(path: str):
    m = re.search(r"r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else None


def load_history(paths) -> list[dict]:
    """Load + normalize rounds, sorted by round number; unreadable or
    non-bench files are skipped with a warning."""
    rounds = []
    for path in paths:
        try:
            with open(path, encoding="utf-8") as fh:
                obj = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"warning: skipping {path}: {e}", file=sys.stderr)
            continue
        rec = normalize_round(obj, fallback_round=_round_from_name(path))
        if rec is None:
            print(f"warning: {path} is not a bench round artifact",
                  file=sys.stderr)
            continue
        rec["path"] = os.path.basename(path)
        rounds.append(rec)
    rounds.sort(key=lambda r: (r["round"] is None, r["round"]))
    return rounds


def compare(rounds: list[dict], tolerance_pct: float) -> dict:
    """Consecutive-pair deltas + tolerance violations over the gated
    fields.  Wall-clock metrics (unit 's') regress UP; rate metrics
    regress DOWN."""
    deltas, violations = [], []
    for prev, cur in zip(rounds, rounds[1:]):
        pair = {"from": prev["round"], "to": cur["round"], "fields": {}}
        lower_is_better = cur.get("unit") == "s"
        for name in ("value",) + GATED_FIELDS:
            a = _dig(prev["fields"], name) if name != "value" \
                else prev["value"]
            b = _dig(cur["fields"], name) if name != "value" \
                else cur["value"]
            if a is None or b is None:
                continue
            if a == 0 and not (name in LOWER_IS_BETTER_FIELDS and b > 0):
                # rate fields can't percent-gate off a zero baseline, but a
                # lower-is-better COUNT going 0 -> nonzero is exactly the
                # transition the gate exists for (e.g. a reappearing
                # bposd.host_round_trips)
                continue
            delta_pct = (b - a) / (abs(a) if a else 1.0) * 100.0
            field_lower = (lower_is_better if name == "value"
                           else name in LOWER_IS_BETTER_FIELDS)
            regressed = (delta_pct > tolerance_pct if field_lower
                         else delta_pct < -tolerance_pct)
            pair["fields"][name] = {
                "from": a, "to": b, "delta_pct": round(delta_pct, 2),
                "regressed": regressed,
            }
            if regressed:
                violations.append({
                    "from_round": prev["round"], "to_round": cur["round"],
                    "field": name, "delta_pct": round(delta_pct, 2),
                })
        deltas.append(pair)
    return {
        "tolerance_pct": tolerance_pct,
        "rounds": [{k: r[k] for k in
                    ("round", "schema", "metric", "value", "unit", "path")}
                   for r in rounds],
        "deltas": deltas,
        "violations": violations,
    }


def _band(delta_pct: float | None, tol: float,
          lower_is_better: bool = False) -> str:
    if delta_pct is None:
        return ""
    good = -delta_pct if lower_is_better else delta_pct
    if good < -tol:
        return "REGRESSED"
    if good > tol:
        return "improved"
    return "within band"


def render(cmp: dict) -> str:
    tol = cmp["tolerance_pct"]
    L = [f"== bench trajectory (tolerance ±{tol}%) =="]
    prev_val = None
    for r in cmp["rounds"]:
        delta = (None if prev_val in (None, 0)
                 else (r["value"] - prev_val) / abs(prev_val) * 100.0)
        d_txt = f"{delta:+8.2f}%" if delta is not None else " " * 9
        # wall-clock rounds (unit 's') improve DOWN — labels must agree
        # with the gate logic in compare()
        band = _band(delta, tol, lower_is_better=r["unit"] == "s")
        L.append(f"  r{r['round']:>02}  {r['value']:>14,.1f} {r['unit']:<8}"
                 f"{d_txt}  {band:<12} ({r['path']})")
        prev_val = r["value"]
    if cmp["rounds"]:
        L.append(f"  metric: {cmp['rounds'][-1]['metric']}")
    stage_rows = [
        (p, name, f)
        for p in cmp["deltas"] for name, f in p["fields"].items()
        if name != "value"
    ]
    if stage_rows:
        L.append("-- gated stage fields --")
        for p, name, f in stage_rows:
            L.append(f"  r{p['from']:>02}->r{p['to']:>02}  {name:<36}"
                     f"{f['delta_pct']:+8.2f}%  "
                     f"{_band(f['delta_pct'], tol, name in LOWER_IS_BETTER_FIELDS)}")
    if cmp["violations"]:
        L.append("-- VIOLATIONS --")
        for v in cmp["violations"]:
            L.append(f"  r{v['from_round']}->r{v['to_round']} "
                     f"{v['field']}: {v['delta_pct']:+.2f}% "
                     f"(tolerance ±{tol}%)")
    else:
        L.append(f"gate: PASS ({len(cmp['rounds'])} rounds, "
                 f"{len(cmp['deltas'])} comparisons)")
    return "\n".join(L)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="round artifacts (default: BENCH_r*.json in the "
                         "repo root, sorted)")
    ap.add_argument("--gate", action="store_true",
                    help="exit 1 when any gated field regressed beyond "
                         "the tolerance")
    ap.add_argument("--tolerance", type=float, default=10.0,
                    help="regression tolerance in percent (default 10; "
                         "the shared-chip history varies ~2%% round to "
                         "round)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    paths = args.paths or sorted(glob.glob(
        os.path.join(REPO, "BENCH_r*.json")))
    rounds = load_history(paths)
    if len(rounds) < 2:
        print(f"need >= 2 rounds to compare, got {len(rounds)}",
              file=sys.stderr)
        return 2
    cmp = compare(rounds, args.tolerance)
    if args.json:
        print(json.dumps(cmp, indent=1))
    else:
        print(render(cmp))
    if args.gate and cmp["violations"]:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
