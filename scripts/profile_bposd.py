"""Stage-split profiling of the BP+OSD bench mode on the live chip.

Times, at the BENCH_MODES `bposd` operating point (hgp_34_n625, p=0.05,
BPOSD(osd_e, 10, N/10 iters)):

  * BP alone (converged + posteriors)
  * device OSD at osd_order=0 (elimination + OSD-0 solve)
  * device OSD at osd_order=10 (adds the OSD-E scoring scan)
  * the full BPOSD decode_device path (compaction tiers included)

for OSD batch sizes matching the compaction tiers, so VERDICT r3 #5's
"profile the split between elimination and OSD-E scoring" has real numbers.

Usage: python scripts/profile_bposd.py [batch]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from qldpc_fault_tolerance_tpu.codes import load_code
from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder
from qldpc_fault_tolerance_tpu.decoders.bp_decoders import decode_device
from qldpc_fault_tolerance_tpu.ops import bp
from qldpc_fault_tolerance_tpu.ops.osd_device import (
    build_osd_plan,
    osd_decode_values,
)


def timeit(fn, *args, reps=10, **kw):
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps, out


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = load_code(os.path.join(here, "codes_lib_tpu", "hgp_34_n625.npz"))
    p = 0.05
    two_thirds = 2 * p / 3
    mi = int(code.N / 10)
    dec = BPOSD_Decoder(code.hx, np.full(code.N, two_thirds), max_iter=mi,
                        osd_method="osd_e", osd_order=10)
    key = jax.random.PRNGKey(0)
    err = jax.random.bernoulli(key, two_thirds, (batch, code.N))
    synd = ((err.astype(jnp.uint8) @ jnp.asarray(code.hx.T)) % 2).astype(
        jnp.uint8)

    graph = bp.build_tanner_graph(code.hx)
    llr0 = bp.llr_from_probs(np.full(code.N, two_thirds))

    @jax.jit
    def bp_only(synd):
        return bp.bp_decode(graph, synd, llr0, max_iter=mi)

    t_bp, res = timeit(bp_only, synd)
    conv = np.asarray(res.converged)
    print(f"batch={batch}  BP({mi} iters): {t_bp * 1e3:.1f} ms  "
          f"converged={conv.mean():.3f}  n_bad={int((~conv).sum())}")

    plan = build_osd_plan(code.hx, np.full(code.N, two_thirds))
    llrs = jnp.asarray(res.posterior_llr)
    for sub in sorted({256, 512, batch}):
        if sub > batch:
            continue
        s_sub, l_sub = synd[:sub], llrs[:sub]
        for order, label in ((0, "OSD-0 (elim+solve)"),
                             (10, "OSD-E order 10")):
            fn = jax.jit(lambda s, l, o=order: osd_decode_values(
                (plan.n, plan.rank, o, 256,
                 os.environ.get("QLDPC_OSD_ELIM", "pallas")),
                plan.packed, plan.cost, s, l))
            t, _ = timeit(fn, s_sub, l_sub)
            print(f"  osd batch={sub:5d} {label:18s}: {t * 1e3:7.1f} ms  "
                  f"({sub / t:8.0f} shots/s)")

    @jax.jit
    def full(synd):
        return decode_device(dec.device_static, dec.device_state, synd)

    t_full, _ = timeit(full, synd)
    print(f"full BPOSD decode_device: {t_full * 1e3:.1f} ms  "
          f"({batch / t_full:.0f} shots/s)")


if __name__ == "__main__":
    main()
