"""Thin wrapper: stage-split BP+OSD timing moved to
``scripts/perf_report.py bposd`` (the ISSUE-6 performance-attribution CLI).

Usage: python scripts/profile_bposd.py [batch]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from perf_report import cmd_bposd  # noqa: E402


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    return cmd_bposd(batch)


if __name__ == "__main__":
    raise SystemExit(main())
