"""Physics-parity suite: reproduce the reference's published threshold numbers.

Each experiment replays a Threshold-checkpoint notebook cell exactly — same
codes, same p-grid, same decoder settings, same (quirky) driver conventions —
and fits p_c with the notebook's own two-stage ThresholdEst (cell 2:
per-code log-log distance fit, then a joint EmpericalFit).  Published values
are the checkpoint cell outputs, tabulated in BASELINE.md.

Driver conventions faithfully mirrored (all verified against the checkpoint
source, not the current reference library):
  * CodeFamilyPhenlThreshold (cell 3) leaves the simulator's syndrome-flip
    probability at its default q=0 — the [H|I] decoder carries 2p/3 channel
    columns for syndrome errors that never occur.
  * The published runs used even cycle counts, predating the odd-cycles
    assert now in src/Simulators.py:353 — the per-cycle inversion is applied
    here directly, without the parity-breaking assert.
  * dec1 max_iter = int(N/30) (1 iteration for the d5 toric code), dec2 =
    BPOSD(int(N/10), osd_e, order 10), both minimum_sum with msf 0.625.

Usage:
  python scripts/parity.py toric_phenl [--seeds 2] [--scale 1]
  python scripts/parity.py hgp_phenl --cycles 6
  python scripts/parity.py toric_circuit --cycles 10

Results append to codes_lib_tpu/../PARITY_results.jsonl; summarize with
scripts/parity_report.py.
"""
import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from scipy.optimize import curve_fit  # noqa: E402

from qldpc_fault_tolerance_tpu.codes import hgp, load_code, ring_code  # noqa: E402
from qldpc_fault_tolerance_tpu.decoders import BPDecoder, BPOSD_Decoder  # noqa: E402
from qldpc_fault_tolerance_tpu.sim import (  # noqa: E402
    CodeSimulator_Circuit,
    CodeSimulator_Phenon,
)

RESULTS = os.path.join(REPO, "PARITY_results.jsonl")


# ---------------------------------------------------------------------------
# notebook fit machinery (Threshold ckpt cells 1-2)
def _FitDistance_log(logp, A, d):
    return A + (d / 2) * logp


def _EmpericalFit(xdata_tuple, pc, A):
    p, d = xdata_tuple
    return A * (p / pc) ** (d / 2)


def notebook_threshold_est(p_list, wer_array):
    """Threshold ckpt cell 2: per-code distance fit then joint (pc, A) fit."""
    num_code, num_p = wer_array.shape
    d_list = []
    for row in wer_array:
        popt, _ = curve_fit(
            _FitDistance_log, np.log10(np.asarray(p_list)),
            np.log10(np.asarray(row) + 1e-6), p0=(0.08, 3),
        )
        d_list.append(popt[1])
    fit_p = np.tile(np.asarray(p_list), num_code)
    fit_d = np.repeat(np.asarray(d_list), num_p)
    fit_X = np.vstack([fit_p, fit_d])
    fit_Z = wer_array.reshape(-1)
    popt, _ = curve_fit(_EmpericalFit, fit_X, fit_Z, p0=(0.04, 0.1))
    return float(popt[0]), float(popt[1]), [float(d) for d in d_list]


def wer_notebook(count, samples, K, cycles):
    """Per-qubit-per-cycle inversion without the odd-cycles assert (the
    published runs used even cycle counts)."""
    ler = count / samples
    plq = 1.0 - (1 - ler) ** (1 / K)
    if plq <= 0.5:
        return (1.0 - (1 - 2 * plq) ** (1 / cycles)) / 2
    return (1.0 + (-1 + 2 * plq) ** (1 / cycles)) / 2


# ---------------------------------------------------------------------------
def toric_codes():
    return [hgp(ring_code(d), ring_code(d), name=f"toric_d{d}")
            for d in (5, 9, 13)]


def hgp_codes():
    lib = os.path.join(REPO, "codes_lib_tpu")
    return [load_code(os.path.join(lib, f"hgp_34_{t}.npz"))
            for t in ("n225", "n625", "n1600")]


def phenl_cell_wer(code, eval_p, cycles, samples, seed, batch_size):
    """CodeFamilyPhenlThreshold inner loop (Threshold ckpt cell 3)."""
    pauli = [eval_p / 3] * 3
    two_thirds = pauli[0] + pauli[1]
    m = code.hx.shape[0]
    ext_x = np.hstack([code.hz, np.eye(code.hz.shape[0], dtype=np.uint8)])
    ext_z = np.hstack([code.hx, np.eye(m, dtype=np.uint8)])
    dec1_x = BPDecoder(ext_x, two_thirds * np.ones(ext_x.shape[1]),
                       max_iter=int(code.N / 30), bp_method="minimum_sum",
                       ms_scaling_factor=0.625)
    dec1_z = BPDecoder(ext_z, two_thirds * np.ones(ext_z.shape[1]),
                       max_iter=int(code.N / 30), bp_method="minimum_sum",
                       ms_scaling_factor=0.625)
    dec2_x = BPOSD_Decoder(code.hz, two_thirds * np.ones(code.N),
                           max_iter=int(code.N / 10), bp_method="minimum_sum",
                           ms_scaling_factor=0.625, osd_method="osd_e",
                           osd_order=10)
    dec2_z = BPOSD_Decoder(code.hx, two_thirds * np.ones(code.N),
                           max_iter=int(code.N / 10), bp_method="minimum_sum",
                           ms_scaling_factor=0.625, osd_method="osd_e",
                           osd_order=10)
    sim = CodeSimulator_Phenon(
        code=code, decoder1_x=dec1_x, decoder1_z=dec1_z,
        decoder2_x=dec2_x, decoder2_z=dec2_z, pauli_error_probs=pauli,
        q=0,  # notebook leaves the default — see module docstring
        seed=seed, batch_size=batch_size,
    )
    count, total = sim._count_failures(cycles, samples)
    return wer_notebook(count, total, code.K, cycles)


def circuit_cell_wer(code, eval_p, cycles, samples, seed, batch_size,
                     circuit_type="coloration"):
    """CodeFamilyCircuitThreshold inner loop (Threshold ckpt cell 4)."""
    p = eval_p
    error_params = {"p_i": 0, "p_state_p": 0, "p_m": 0, "p_CX": p,
                    "p_idling_gate": 0}
    p_data = 3 * 6 * (8 / 15) * p
    p_synd = 7 * (8 / 15) * p
    ext = np.hstack([code.hx, np.eye(code.hx.shape[0], dtype=np.uint8)])
    dec1_z = BPDecoder(
        ext,
        np.hstack([p_data * np.ones(code.hx.shape[1]),
                   p_synd * np.ones(code.hx.shape[0])]),
        max_iter=int(code.N / 30), bp_method="minimum_sum",
        ms_scaling_factor=0.625)
    dec2_z = BPOSD_Decoder(code.hx, p * np.ones(code.N),
                           max_iter=int(code.N / 10), bp_method="minimum_sum",
                           ms_scaling_factor=0.625, osd_method="osd_e",
                           osd_order=10)
    sim = CodeSimulator_Circuit(
        code=code, decoder1_z=dec1_z, decoder2_z=dec2_z, p=p,
        num_cycles=cycles, error_params=error_params,
        circuit_type=circuit_type, seed=seed, batch_size=batch_size,
    )
    sim._generate_circuit()
    count, total = sim._count_failures(samples)
    return wer_notebook(count, total, code.K, cycles)


EXPERIMENTS = {
    # Threshold ckpt cell 25; published p_c per cycles:
    "toric_phenl": dict(
        codes=toric_codes, cell=phenl_cell_wer,
        p_list=np.linspace(0.8e-2, 2e-2, 6), samples_base=10000,
        published={6: 0.0497, 10: 0.0303, 15: 0.0254, 20: 0.0207,
                   25: 0.0169, 30: 0.0156},
        source="Threshold ckpt cell 25",
    ),
    # Threshold ckpt cell 12 (codes n225 exact, n625/n1600 statistically
    # equivalent regenerations — see codes_lib_tpu/GENERATION.json)
    "hgp_phenl": dict(
        codes=hgp_codes, cell=phenl_cell_wer,
        p_list=np.linspace(1e-2, 3e-2, 6), samples_base=4000,
        published={6: 0.0900, 10: 0.0752, 15: 0.0632, 20: 0.0517, 25: 0.0568},
        source="Threshold ckpt cell 12",
    ),
    # Threshold ckpt cell 39 (cycles-6 published value 0.0418 is a fit
    # outlier per BASELINE.md)
    "toric_circuit": dict(
        codes=toric_codes, cell=circuit_cell_wer,
        p_list=np.linspace(0.7e-3, 2e-3, 6), samples_base=50000,
        published={6: 0.0418, 10: 0.0054, 15: 0.0041, 20: 0.0027,
                   25: 0.0022, 30: 0.0020},
        source="Threshold ckpt cell 39",
    ),
    # Threshold ckpt cell 29 (HGP circuit-level)
    "hgp_circuit": dict(
        codes=hgp_codes, cell=circuit_cell_wer,
        p_list=np.linspace(1e-3, 3.5e-3, 6), samples_base=6000,
        published={3: 0.0392, 6: 0.0134, 10: 0.0072, 15: 0.0069, 20: 0.0063},
        source="Threshold ckpt cell 29",
    ),
}


def _run_cell_with_retry(cell, *args, retries: int = 3, **kwargs):
    """The tunneled TPU worker intermittently crashes mid-dispatch on large
    programs (infrastructure flake — it auto-restarts).  Retry the cell
    after dropping all device-resident caches; results are unaffected
    (cells are deterministic in their seed)."""
    import jax

    import qldpc_fault_tolerance_tpu as q

    for attempt in range(retries):
        try:
            return cell(*args, **kwargs)
        except jax.errors.JaxRuntimeError as e:
            if attempt == retries - 1:
                raise
            print(f"TPU worker error ({str(e).splitlines()[0][:90]}); "
                  f"resetting device caches and retrying "
                  f"({attempt + 1}/{retries})", file=sys.stderr)
            q.reset_device_state()
            time.sleep(10)


def run_experiment(name, cycles_list, seeds, scale, batch_size,
                   seed_start=0, circuit_type=None):
    exp = EXPERIMENTS[name]
    codes = exp["codes"]()
    cell_kwargs = {}
    if circuit_type is not None:
        cell_kwargs["circuit_type"] = circuit_type
    for cycles in cycles_list:
        published = exp["published"].get(cycles)
        samples = int(exp["samples_base"] * 3 / cycles * scale)
        for seed in range(seed_start, seed_start + seeds):
            t0 = time.time()
            wer = np.zeros((len(codes), len(exp["p_list"])))
            for ci, code in enumerate(codes):
                for pi, p in enumerate(exp["p_list"]):
                    wer[ci, pi] = _run_cell_with_retry(
                        exp["cell"], code, p, cycles, samples,
                        seed=seed * 7919 + ci * 101 + pi,
                        batch_size=batch_size, **cell_kwargs,
                    )
            try:
                pc, A, d_list = notebook_threshold_est(exp["p_list"], wer)
            except RuntimeError as e:  # curve_fit failure — record it
                pc, A, d_list = float("nan"), float("nan"), []
                print(f"fit failed: {e}")
            rec = {
                "experiment": name, "cycles": cycles, "seed": seed,
                "circuit_type": circuit_type,
                "samples_per_cell": samples, "p_c": pc, "A": A,
                "d_eff": d_list, "published_p_c": published,
                "wer": wer.tolist(), "p_list": list(map(float, exp["p_list"])),
                "elapsed_s": round(time.time() - t0, 1),
                "source": exp["source"],
            }
            with open(RESULTS, "a") as f:
                f.write(json.dumps(rec) + "\n")
            print(json.dumps({k: rec[k] for k in
                              ("experiment", "cycles", "seed", "p_c",
                               "published_p_c", "elapsed_s")}))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("experiment", choices=list(EXPERIMENTS))
    ap.add_argument("--cycles", type=int, nargs="*", default=None)
    ap.add_argument("--seeds", type=int, default=2)
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--batch-size", type=int, default=2048)
    ap.add_argument("--seed-start", type=int, default=0)
    ap.add_argument("--no-record", action="store_true",
                    help="don't append to PARITY_results.jsonl (warmup runs)")
    ap.add_argument("--circuit-type", default=None,
                    choices=["coloration", "coloration_hk", "random"],
                    help="override the circuit engines' CX scheduler (A/B "
                         "experiments for schedule sensitivity)")
    ap.add_argument("--warmup", action="store_true",
                    help="run a tiny-scale pass of the same cells first so "
                         "the recorded elapsed_s measures the warm-process "
                         "sweep (the reference's notebook timings are also "
                         "warm: each cycles entry runs after the previous "
                         "one in the same kernel session)")
    args = ap.parse_args()
    global RESULTS
    if args.no_record:
        RESULTS = os.devnull
    if args.warmup:
        real_results = RESULTS
        RESULTS = os.devnull
        run_experiment(args.experiment,
                       (args.cycles or sorted(EXPERIMENTS[args.experiment]
                                              ["published"]))[:1],
                       1, 0.003, args.batch_size, seed_start=args.seed_start,
                       circuit_type=args.circuit_type)
        RESULTS = real_results
    exp = EXPERIMENTS[args.experiment]
    cycles_list = args.cycles or sorted(exp["published"])
    run_experiment(args.experiment, cycles_list, args.seeds, args.scale,
                   args.batch_size, seed_start=args.seed_start,
                   circuit_type=args.circuit_type)


if __name__ == "__main__":
    main()
