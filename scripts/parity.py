"""Physics-parity suite: reproduce the reference's published threshold numbers.

Each experiment replays a Threshold-checkpoint notebook cell exactly — same
codes, same p-grid, same decoder settings, same (quirky) driver conventions —
and fits p_c with the notebook's own two-stage ThresholdEst (cell 2:
per-code log-log distance fit, then a joint EmpericalFit).  Published values
are the checkpoint cell outputs, tabulated in BASELINE.md.

Driver conventions faithfully mirrored (all verified against the checkpoint
source, not the current reference library):
  * CodeFamilyPhenlThreshold (cell 3) leaves the simulator's syndrome-flip
    probability at its default q=0 — the [H|I] decoder carries 2p/3 channel
    columns for syndrome errors that never occur.
  * The published runs used even cycle counts, predating the odd-cycles
    assert now in src/Simulators.py:353 — the per-cycle inversion is applied
    here directly, without the parity-breaking assert.
  * dec1 max_iter = int(N/30) (1 iteration for the d5 toric code), dec2 =
    BPOSD(int(N/10), osd_e, order 10), both minimum_sum with msf 0.625.

Usage:
  python scripts/parity.py toric_phenl [--seeds 2] [--scale 1]
  python scripts/parity.py hgp_phenl --cycles 6
  python scripts/parity.py toric_circuit --cycles 10

Results append to codes_lib_tpu/../PARITY_results.jsonl; summarize with
scripts/parity_report.py.
"""
import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from scipy.optimize import curve_fit  # noqa: E402

from qldpc_fault_tolerance_tpu.codes import (  # noqa: E402
    hgp,
    load_code,
    load_mat_pair,
    ring_code,
)
from qldpc_fault_tolerance_tpu.decoders import BPDecoder, BPOSD_Decoder  # noqa: E402
from qldpc_fault_tolerance_tpu.sim import (  # noqa: E402
    CodeSimulator_Circuit,
    CodeSimulator_Phenon,
)

RESULTS = os.path.join(REPO, "PARITY_results.jsonl")


# ---------------------------------------------------------------------------
# notebook fit machinery (Threshold ckpt cells 1-2)
def _FitDistance_log(logp, A, d):
    return A + (d / 2) * logp


def _EmpericalFit(xdata_tuple, pc, A):
    p, d = xdata_tuple
    return A * (p / pc) ** (d / 2)


def notebook_threshold_est(p_list, wer_array):
    """Threshold ckpt cell 2: per-code distance fit then joint (pc, A) fit."""
    num_code, num_p = wer_array.shape
    d_list = []
    for row in wer_array:
        popt, _ = curve_fit(
            _FitDistance_log, np.log10(np.asarray(p_list)),
            np.log10(np.asarray(row) + 1e-6), p0=(0.08, 3),
        )
        d_list.append(popt[1])
    fit_p = np.tile(np.asarray(p_list), num_code)
    fit_d = np.repeat(np.asarray(d_list), num_p)
    fit_X = np.vstack([fit_p, fit_d])
    fit_Z = wer_array.reshape(-1)
    popt, _ = curve_fit(_EmpericalFit, fit_X, fit_Z, p0=(0.04, 0.1))
    return float(popt[0]), float(popt[1]), [float(d) for d in d_list]


def wer_notebook(count, samples, K, cycles):
    """Per-qubit-per-cycle inversion without the odd-cycles assert (the
    published runs used even cycle counts)."""
    ler = count / samples
    plq = 1.0 - (1 - ler) ** (1 / K)
    if plq <= 0.5:
        return (1.0 - (1 - 2 * plq) ** (1 / cycles)) / 2
    return (1.0 + (-1 + 2 * plq) ** (1 / cycles)) / 2


# ---------------------------------------------------------------------------
def toric_codes():
    return [hgp(ring_code(d), ring_code(d), name=f"toric_d{d}")
            for d in (5, 9, 13)]


def hgp_codes(tags=("n225", "n625", "n1600")):
    """Threshold ckpt cells 12/29 sweep the 3-member family; pass
    ``tags=("n225","n625","n1225","n1600")`` for the 4-member variant
    (Single-Shot cell 4's family) — used for the per-member d_eff table,
    NOT for published-p_c comparison (the published fits are 3-member)."""
    lib = os.path.join(REPO, "codes_lib_tpu")
    return [load_code(os.path.join(lib, f"hgp_34_{t}.npz")) for t in tags]


# Root of the reference .mat code matrices (LP / GBC families).  Overridable
# because the mount point is deployment-specific — CI images and laptops
# don't have /root/reference; point QLDPC_REF_CODES_LIB at a checkout of the
# reference repo's codes_lib to run those parity families.
REF_CODES_LIB = os.environ.get("QLDPC_REF_CODES_LIB",
                               "/root/reference/codes_lib")


def lp_codes():
    """Threshold ckpt cell 7: the (3,8) lifted-product family.  Unlike the
    hgp_34 family these load BIT-EXACTLY from the mounted .mat matrices —
    no regeneration caveat applies, so z>2 here is a true MISMATCH."""
    return [load_mat_pair(os.path.join(
        REF_CODES_LIB, f"LP_Matg8_L{L}_Dmin{D}_hx.mat"))
        for L, D in ((16, 12), (21, 16), (30, 20))]


def gbc_codes():
    """Threshold ckpt cell 8: generalized bicycle codes A1-A3 (bit-exact
    .mat input matrices, same caveat-free status as lp_codes)."""
    return [load_mat_pair(os.path.join(
        REF_CODES_LIB, f"GenBicycleA{i}_hx.mat")) for i in (1, 2, 3)]


def phenl_cell_wer(code, eval_p, cycles, samples, seed, batch_size):
    """CodeFamilyPhenlThreshold inner loop (Threshold ckpt cell 3)."""
    pauli = [eval_p / 3] * 3
    two_thirds = pauli[0] + pauli[1]
    m = code.hx.shape[0]
    ext_x = np.hstack([code.hz, np.eye(code.hz.shape[0], dtype=np.uint8)])
    ext_z = np.hstack([code.hx, np.eye(m, dtype=np.uint8)])
    dec1_x = BPDecoder(ext_x, two_thirds * np.ones(ext_x.shape[1]),
                       max_iter=int(code.N / 30), bp_method="minimum_sum",
                       ms_scaling_factor=0.625)
    dec1_z = BPDecoder(ext_z, two_thirds * np.ones(ext_z.shape[1]),
                       max_iter=int(code.N / 30), bp_method="minimum_sum",
                       ms_scaling_factor=0.625)
    dec2_x = BPOSD_Decoder(code.hz, two_thirds * np.ones(code.N),
                           max_iter=int(code.N / 10), bp_method="minimum_sum",
                           ms_scaling_factor=0.625, osd_method="osd_e",
                           osd_order=10)
    dec2_z = BPOSD_Decoder(code.hx, two_thirds * np.ones(code.N),
                           max_iter=int(code.N / 10), bp_method="minimum_sum",
                           ms_scaling_factor=0.625, osd_method="osd_e",
                           osd_order=10)
    sim = CodeSimulator_Phenon(
        code=code, decoder1_x=dec1_x, decoder1_z=dec1_z,
        decoder2_x=dec2_x, decoder2_z=dec2_z, pauli_error_probs=pauli,
        q=0,  # notebook leaves the default — see module docstring
        seed=seed, batch_size=batch_size,
    )
    count, total = sim._count_failures(cycles, samples)
    return wer_notebook(count, total, code.K, cycles)


def make_circuit_decoders(code, p, msf1=0.625, msf2=0.625,
                          mi1=None, mi2=None, method1="minimum_sum",
                          method2="minimum_sum"):
    """The notebook's circuit-threshold decoder recipe (Threshold ckpt
    cell 4) — THE shared single source for every A/B script (ab_bp_schedule,
    ab_frame_sim, ab_iteration import this so arm comparisons can never
    drift from the parity baseline): dec1 = BP on [hx|I] with
    p_data=3*6*(8/15)p / p_synd=7*(8/15)p priors and int(N/30) iterations;
    dec2 = BPOSD(osd_e, order 10) on hx with int(N/10) iterations."""
    p_data = 3 * 6 * (8 / 15) * p
    p_synd = 7 * (8 / 15) * p
    m = code.hx.shape[0]
    ext = np.hstack([code.hx, np.eye(m, dtype=np.uint8)])
    dec1 = BPDecoder(
        ext,
        np.hstack([p_data * np.ones(code.hx.shape[1]),
                   p_synd * np.ones(m)]),
        max_iter=max(1, int(code.N / 30) if mi1 is None else mi1),
        bp_method=method1, ms_scaling_factor=msf1)
    dec2 = BPOSD_Decoder(
        code.hx, p * np.ones(code.N),
        max_iter=max(1, int(code.N / 10) if mi2 is None else mi2),
        bp_method=method2, ms_scaling_factor=msf2,
        osd_method="osd_e", osd_order=10)
    return dec1, dec2


def circuit_cell_wer(code, eval_p, cycles, samples, seed, batch_size,
                     circuit_type="coloration", msf=0.625, msf1=None,
                     msf2=None):
    """CodeFamilyCircuitThreshold inner loop (Threshold ckpt cell 4).

    ``msf`` overrides the min-sum scaling factor of both decoders;
    ``msf1``/``msf2`` override them separately (the notebook's dec1 is an
    `ldpc.bp_decoder`, dec2 a `bposd.bposd_decoder` — DIFFERENT binaries
    that may treat ms_scaling_factor differently; PARITY_r4.md msf A/B)."""
    msf1 = msf if msf1 is None else msf1
    msf2 = msf if msf2 is None else msf2
    p = eval_p
    error_params = {"p_i": 0, "p_state_p": 0, "p_m": 0, "p_CX": p,
                    "p_idling_gate": 0}
    dec1_z, dec2_z = make_circuit_decoders(code, p, msf1=msf1, msf2=msf2)
    sim = CodeSimulator_Circuit(
        code=code, decoder1_z=dec1_z, decoder2_z=dec2_z, p=p,
        num_cycles=cycles, error_params=error_params,
        circuit_type=circuit_type, seed=seed, batch_size=batch_size,
    )
    sim._generate_circuit()
    count, total = sim._count_failures(samples)
    return wer_notebook(count, total, code.K, cycles)


EXPERIMENTS = {
    # Threshold ckpt cell 25; published p_c per cycles:
    "toric_phenl": dict(
        codes=toric_codes, cell=phenl_cell_wer,
        p_list=np.linspace(0.8e-2, 2e-2, 6), samples_base=10000,
        published={6: 0.0497, 10: 0.0303, 15: 0.0254, 20: 0.0207,
                   25: 0.0169, 30: 0.0156},
        source="Threshold ckpt cell 25",
    ),
    # Threshold ckpt cell 12 (codes n225 exact, n625/n1600 statistically
    # equivalent regenerations — see codes_lib_tpu/GENERATION.json)
    "hgp_phenl": dict(
        codes=hgp_codes, cell=phenl_cell_wer,
        p_list=np.linspace(1e-2, 3e-2, 6), samples_base=4000,
        published={6: 0.0900, 10: 0.0752, 15: 0.0632, 20: 0.0517, 25: 0.0568},
        source="Threshold ckpt cell 12",
    ),
    # Threshold ckpt cell 39 (cycles-6 published value 0.0418 is a fit
    # outlier per BASELINE.md)
    "toric_circuit": dict(
        codes=toric_codes, cell=circuit_cell_wer,
        p_list=np.linspace(0.7e-3, 2e-3, 6), samples_base=50000,
        published={6: 0.0418, 10: 0.0054, 15: 0.0041, 20: 0.0027,
                   25: 0.0022, 30: 0.0020},
        source="Threshold ckpt cell 39",
    ),
    # Threshold ckpt cell 29 (HGP circuit-level)
    "hgp_circuit": dict(
        codes=hgp_codes, cell=circuit_cell_wer,
        p_list=np.linspace(1e-3, 3.5e-3, 6), samples_base=6000,
        published={3: 0.0392, 6: 0.0134, 10: 0.0072, 15: 0.0069, 20: 0.0063},
        source="Threshold ckpt cell 29",
    ),
    # Threshold ckpt cell 16 (LP phenomenological, 4k samples).  Published
    # p_c kept at full checkpoint precision; the 25/30-cycle values (0.0288 /
    # 0.0959) are visibly broken fits in the reference's own output (A drops
    # 10x / jumps 76x between neighboring rows).
    "lp_phenl": dict(
        codes=lp_codes, cell=phenl_cell_wer,
        p_list=np.linspace(2e-2, 3.5e-2, 6), samples_base=4000,
        published={6: 0.063376, 10: 0.050116, 15: 0.042953, 20: 0.043911,
                   25: 0.028826, 30: 0.095915},
        # the reference's own 25/30-cycle fits are visibly broken (A drops
        # 10x / jumps 76x between neighboring rows); suspect rows are
        # tabulated with informational z but excluded from the headline
        # MATCH/MISMATCH tally (parity_report.py PUB-SUSPECT class)
        suspect_cycles={25, 30},
        source="Threshold ckpt cell 16",
    ),
    # Threshold ckpt cell 20 (LP phenomenological, 12k samples, 20-30 cycles
    # on a lower p-grid) — the executed-notebook single-run numbers hinted at
    # divergence here; this experiment adjudicates it with multi-seed z.
    "lp_phenl_12k": dict(
        codes=lp_codes, cell=phenl_cell_wer,
        p_list=np.linspace(1.5e-2, 3e-2, 6), samples_base=12000,
        published={20: 0.043342, 25: 0.055146, 30: 0.037340},
        source="Threshold ckpt cell 20",
    ),
    # Threshold ckpt cell 32 (LP circuit-level)
    "lp_circuit": dict(
        codes=lp_codes, cell=circuit_cell_wer,
        p_list=np.linspace(2e-3, 4.5e-3, 6), samples_base=10000,
        published={3: 0.008171, 6: 0.005905, 10: 0.005808, 15: 0.005914,
                   20: 0.005833},
        source="Threshold ckpt cell 32",
    ),
    # Threshold ckpt cell 36 (GBC circuit-level)
    "gbc_circuit": dict(
        codes=gbc_codes, cell=circuit_cell_wer,
        p_list=np.linspace(1e-3, 4e-3, 7), samples_base=30000,
        published={3: 0.009290, 6: 0.006377, 10: 0.005385, 15: 0.004735,
                   20: 0.004192, 25: 0.004096, 30: 0.003705},
        source="Threshold ckpt cell 36",
    ),
}


# Cell-level retry: the tunneled TPU worker intermittently crashes
# mid-dispatch on large programs (infrastructure flake — it auto-restarts)
# and can take minutes to come back, so the backoff grows: quick retries in
# ~30 s all land on the dead worker and burn the whole budget (observed
# round 4, hgp_phenl 4-member run).  The library RetryPolicy
# (utils.resilience) replaces the ad-hoc loop this script used to carry:
# same 15/30/60/120 s schedule (now jittered), same reset_device_state()
# between attempts, but retry decisions/counters/structured log lines are
# identical across parity, sweeps, and user code — and deterministic bugs
# fail FAST instead of burning five attempts.  The engines' own (shorter)
# default policy handles quick flakes underneath; this outer policy is the
# worker-comeback belt.
from qldpc_fault_tolerance_tpu.utils.resilience import RetryPolicy  # noqa: E402

_CELL_POLICY = RetryPolicy(max_attempts=5, base_delay=15.0, backoff=2.0,
                           max_delay=240.0, jitter=0.25, seed=0)


def _run_cell_with_retry(cell, *args, **kwargs):
    """Run one parity cell under the worker-comeback retry policy (results
    are unaffected: cells are deterministic in their seed)."""
    return _CELL_POLICY.run(lambda: cell(*args, **kwargs),
                            label="parity_cell")


def run_experiment(name, cycles_list, seeds, scale, batch_size,
                   seed_start=0, circuit_type=None, members=None, msf=None,
                   p_scale=1.0):
    exp = EXPERIMENTS[name]
    if members and exp["codes"] is not hgp_codes:
        raise SystemExit("--members applies only to the hgp experiments")
    codes = exp["codes"](tuple(members)) if members else exp["codes"]()
    p_list = np.asarray(exp["p_list"]) * p_scale
    cell_kwargs = {}
    if circuit_type is not None:
        cell_kwargs["circuit_type"] = circuit_type
    if msf is not None:
        if exp["cell"] is not circuit_cell_wer:
            raise SystemExit("--msf applies only to the circuit experiments")
        cell_kwargs["msf1"] = msf if msf != "d1only" else 1.0
        if msf == "d1only":
            cell_kwargs["msf2"] = 0.625
        else:
            cell_kwargs["msf2"] = msf
    for cycles in cycles_list:
        published = exp["published"].get(cycles)
        samples = int(exp["samples_base"] * 3 / cycles * scale)
        for seed in range(seed_start, seed_start + seeds):
            t0 = time.time()
            wer = np.zeros((len(codes), len(p_list)))
            for ci, code in enumerate(codes):
                for pi, p in enumerate(p_list):
                    wer[ci, pi] = _run_cell_with_retry(
                        exp["cell"], code, p, cycles, samples,
                        seed=seed * 7919 + ci * 101 + pi,
                        batch_size=batch_size, **cell_kwargs,
                    )
            try:
                pc, A, d_list = notebook_threshold_est(p_list, wer)
            except RuntimeError as e:  # curve_fit failure — record it
                pc, A, d_list = float("nan"), float("nan"), []
                print(f"fit failed: {e}")
            rec = {
                "experiment": name, "cycles": cycles, "seed": seed,
                "circuit_type": circuit_type, "msf": msf,
                "members": [c.name or f"code{ci}"
                            for ci, c in enumerate(codes)] if members else None,
                "samples_per_cell": samples, "p_c": pc, "A": A,
                "d_eff": d_list, "published_p_c": published,
                "wer": wer.tolist(), "p_list": list(map(float, p_list)),
                "elapsed_s": round(time.time() - t0, 1),
                "source": exp["source"],
            }
            if p_scale != 1.0:
                rec["p_scale"] = p_scale
            if cycles in exp.get("suspect_cycles", ()):
                rec["published_suspect"] = True
            with open(RESULTS, "a") as f:
                f.write(json.dumps(rec) + "\n")
            print(json.dumps({k: rec[k] for k in
                              ("experiment", "cycles", "seed", "p_c",
                               "published_p_c", "elapsed_s")}))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("experiment", choices=list(EXPERIMENTS))
    ap.add_argument("--cycles", type=int, nargs="*", default=None)
    ap.add_argument("--seeds", type=int, default=2)
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--batch-size", type=int, default=2048)
    ap.add_argument("--seed-start", type=int, default=0)
    ap.add_argument("--no-record", action="store_true",
                    help="don't append to PARITY_results.jsonl (warmup runs)")
    ap.add_argument("--circuit-type", default=None,
                    choices=["coloration", "coloration_hk", "random"],
                    help="override the circuit engines' CX scheduler (A/B "
                         "experiments for schedule sensitivity)")
    ap.add_argument("--msf", default=None,
                    type=lambda v: v if v == "d1only" else float(v),
                    help="override the circuit cells' ms_scaling_factor "
                         "(msf-1.0 hypothesis A/B, PARITY_r4.md)")
    ap.add_argument("--members", nargs="*", default=None,
                    help="hgp member tags override, e.g. n225 n625 n1225 "
                         "n1600 (d_eff instrument; published p_c rows are "
                         "3-member)")
    ap.add_argument("--p-scale", type=float, default=1.0,
                    help="multiply the experiment's p-grid (re-grid for "
                         "regenerated families whose crossing sits off the "
                         "published grid — rows are tagged p_scale and "
                         "reported as REGEN-DIFF(regridded), never mixed "
                         "into the exact-grid comparison)")
    ap.add_argument("--warmup", action="store_true",
                    help="run a tiny-scale pass of the same cells first so "
                         "the recorded elapsed_s measures the warm-process "
                         "sweep (the reference's notebook timings are also "
                         "warm: each cycles entry runs after the previous "
                         "one in the same kernel session)")
    args = ap.parse_args()
    global RESULTS
    if os.environ.get("QLDPC_TELEMETRY_JSONL"):
        # bench.py (and operators) opt sweeps into the telemetry event
        # stream via env; the final snapshot lands when the run exits
        import atexit

        from qldpc_fault_tolerance_tpu.utils import telemetry

        telemetry.enable()  # enable() reads QLDPC_TELEMETRY_JSONL itself
        atexit.register(telemetry.write_snapshot_event)
    if args.no_record:
        RESULTS = os.devnull
    if args.warmup:
        real_results = RESULTS
        RESULTS = os.devnull
        run_experiment(args.experiment,
                       (args.cycles or sorted(EXPERIMENTS[args.experiment]
                                              ["published"]))[:1],
                       1, 0.003, args.batch_size, seed_start=args.seed_start,
                       circuit_type=args.circuit_type, members=args.members,
                       msf=args.msf, p_scale=args.p_scale)
        RESULTS = real_results
        if os.environ.get("QLDPC_TELEMETRY_JSONL"):
            # elapsed_s measures the warm sweep alone, so the final
            # snapshot's counters must not include the warmup pass either;
            # the disable/enable cycle also re-baselines the pjit
            # cache-miss retrace fallback past the warmup compiles
            from qldpc_fault_tolerance_tpu.utils import telemetry

            telemetry.disable()
            telemetry.reset()
            telemetry.enable()
    exp = EXPERIMENTS[args.experiment]
    cycles_list = args.cycles or sorted(exp["published"])
    run_experiment(args.experiment, cycles_list, args.seeds, args.scale,
                   args.batch_size, seed_start=args.seed_start,
                   circuit_type=args.circuit_type, members=args.members,
                   msf=args.msf, p_scale=args.p_scale)


if __name__ == "__main__":
    main()
