#!/usr/bin/env python
"""qldpc-lint launcher: ``python scripts/lint.py [--json] [--select ...]``.

Thin wrapper over ``python -m qldpc_fault_tolerance_tpu.analysis`` so the
analyzer runs from a fresh checkout without installing the package.  See
README "Static analysis" for the rule table and suppression syntax.
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from qldpc_fault_tolerance_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
