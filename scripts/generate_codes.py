"""Regenerate the hgp_34 quantum-expander code family.

The reference ships only hgp_34_n225.pkl; the larger codes used throughout
its notebooks (n625 / n1225 / n1600) are listed in .MISSING_LARGE_BLOBS and
absent from the mount, so they are regenerated here as statistically
equivalent codes (SURVEY §7 step 1): random (Δc=4, Δv=3)-biregular seed
codes with girth raised by edge swaps (reference generator
GeneRandGraphsLargeGirthFinal, src/QuantumExanderCodesGene.py:314-330), then
hgp(H, H).

Seeds are fixed and recorded in codes_lib_tpu/GENERATION.json so the family
is reproducible bit-for-bit.

Usage: PYTHONPATH=. python scripts/generate_codes.py [n625 n1225 n1600 n225]
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qldpc_fault_tolerance_tpu.codes import (  # noqa: E402
    gf2,
    hgp,
    improve_girth,
    random_biregular_tanner,
    save_code,
    tanner_girth,
)

OUT_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "codes_lib_tpu")

# (name, n0, target_girth, master_seed): (4,3)-biregular seeds, H is
# (3 n0) x (4 n0); hgp(H,H) gives N = (4 n0)^2 + (3 n0)^2 = 25 n0^2.
#
# Girth targets: 6 is the practical maximum for this family.  A
# (3,4)-biregular girth-8 Tanner graph must satisfy the bipartite Moore
# bound (every depth-3 BFS tree embeds injectively): from any degree-3
# variable node, 3 + 3*2*3 = 21 distinct checks and 1 + 9 = 10 distinct
# variables are required, and from any check, 4 + 4*2*3 = 28 distinct
# variables — i.e. at least 21 checks x 28 variables.  The n625 seed
# (15x20) and n1225 seed (21x28) are below/at that bound; equality at
# 21x28 would make the graph the incidence graph of a generalized
# quadrangle GQ(2,3), which is known not to exist (s+t=5 fails to divide
# st(s+1)(t+1)=72).  So girth 8 is impossible for n625/n1225 and out of
# random-swap reach for n1600 (24x32, barely above the bound).  For
# calibration, the reference's own shipped n225 seed has girth 4
# (/root/reference/codes_lib/hgp_34_n225.pkl, h1 attribute) — girth 6
# here is already strictly better graph quality than the reference's.
FAMILY = {
    "n225": (3, 6, 225001),
    "n625": (5, 6, 625001),
    "n1225": (7, 6, 1225001),
    "n1600": (8, 6, 1600001),
}

REFERENCE_N225_PKL = "/root/reference/codes_lib/hgp_34_n225.pkl"


def extract_reference_seed(pkl_path: str) -> np.ndarray:
    """Pull the 9x12 seed matrix ``h1`` out of the shipped reference pickle.

    The reference's published family member is [[225,17]] — built from a
    rank-8 (hence rank-deficient) 9x12 seed, which a random full-rank draw
    cannot reproduce (K = k^2 + k_T^2 = 16 + 1 = 17 needs the transpose
    logical).  The pickle is a data asset, so the exact seed is recoverable;
    using it makes our n225 the *identical* code, apples-to-apples with
    every published n225 number (BASELINE.md).
    """
    from qldpc_fault_tolerance_tpu.codes.loaders import load_object

    obj = load_object(pkl_path)
    h1 = np.asarray(obj.h1, dtype=np.uint8) % 2
    assert h1.shape == (9, 12), h1.shape
    return h1


def generate_one(tag: str, n0: int, target_girth: int, master_seed: int):
    t0 = time.time()
    if tag == "n225":
        if not os.path.exists(REFERENCE_N225_PKL):
            # a random full-rank draw would give [[225,9]], a *different*
            # code than the published [[225,17]] — refuse rather than
            # silently diverge from GENERATION.json and the tests
            raise FileNotFoundError(
                f"{REFERENCE_N225_PKL} not mounted; n225 must be built from "
                "the exact reference seed (rank-8 9x12) to be [[225,17]]"
            )
        H = extract_reference_seed(REFERENCE_N225_PKL)
        code = hgp(H, H, compute_distance=False, name=f"hgp_34_{tag}")
        save_code(code, os.path.join(OUT_DIR, f"hgp_34_{tag}.npz"))
        np.save(os.path.join(OUT_DIR, f"hgp_34_{tag}_seedH.npy"), H)
        meta = {
            "tag": tag, "n0": n0, "delta_c": 4, "delta_v": 3,
            "seed_source": "reference hgp_34_n225.pkl h1 attribute (exact)",
            "seed_rank": int(gf2.rank(H)),
            "seed_girth": int(tanner_girth(H)),
            "N": int(code.N), "K": int(code.K),
            "elapsed_s": round(time.time() - t0, 1),
        }
        print(json.dumps(meta))
        return meta
    rng = np.random.default_rng(master_seed)
    configured_girth = target_girth
    attempts = 0
    while True:
        attempts += 1
        if attempts % 4 == 0 and target_girth > 6:
            target_girth -= 2
            print(f"{tag}: lowering girth target to {target_girth}")
        H = random_biregular_tanner(n0, 4, 3, rng)
        H, ok = improve_girth(H, target_girth, max_iter=6000, rng=rng)
        if not ok:
            continue
        # full-row-rank seeds give K = (n-m)^2 with no transpose logicals,
        # matching the published family dimensions ([[625,25]], [[1225,49]],
        # [[1600,64]], SURVEY §6)
        if gf2.rank(H) != H.shape[0]:
            continue
        break
    code = hgp(H, H, compute_distance=False, name=f"hgp_34_{tag}")
    path = os.path.join(OUT_DIR, f"hgp_34_{tag}.npz")
    save_code(code, path)
    seed_path = os.path.join(OUT_DIR, f"hgp_34_{tag}_seedH.npy")
    np.save(seed_path, H)
    # reproducibility contract: rerunning this script with the same FAMILY
    # entry replays the identical RNG path (the girth step-down happens at
    # fixed attempt counts); both the configured and the achieved target are
    # recorded so the metadata alone cannot be mistaken for the replay recipe
    meta = {
        "tag": tag, "n0": n0, "delta_c": 4, "delta_v": 3,
        "configured_target_girth": configured_girth,
        "achieved_target_girth": target_girth, "master_seed": master_seed,
        "attempts": attempts, "seed_girth": int(tanner_girth(H)),
        "N": int(code.N), "K": int(code.K),
        "elapsed_s": round(time.time() - t0, 1),
    }
    print(json.dumps(meta))
    return meta


def main():
    os.makedirs(OUT_DIR, exist_ok=True)
    tags = sys.argv[1:] or list(FAMILY)
    metas = []
    meta_path = os.path.join(OUT_DIR, "GENERATION.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            metas = json.load(f)
    done = {m["tag"] for m in metas}
    for tag in tags:
        if tag in done:
            print(f"{tag}: already generated")
            continue
        n0, g, seed = FAMILY[tag]
        metas.append(generate_one(tag, n0, g, seed))
        with open(meta_path, "w") as f:
            json.dump(metas, f, indent=1)


if __name__ == "__main__":
    main()
