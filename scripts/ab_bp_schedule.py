"""BP message-schedule A/B for the circuit-level p_c offset (VERDICT r3 #2c).

The reference decodes with `ldpc.bp_decoder` binaries whose exact min-sum
variant we cannot install in this image (tests/test_golden.py:1-19).  The
era-appropriate ldpc v1 is a FLOODING (parallel) normalized min-sum — the
same schedule our ops/bp.py implements — but ldpc v2 added a serial
schedule, and serial vs flooding min-sum have different fixed points at
the tiny iteration counts the notebooks use (dec1 max_iter = int(N/30) = 1
for the d5 toric code).  This experiment bounds the schedule effect: decode
ONE fixed detector sample set through the reference's round-chain with

  arm flood:      numpy flooding min-sum dec1 + flooding BP+OSD final
  arm serial1:    serial dec1, flooding final
  arm serial_all: serial dec1 AND serial BP stage of the final BPOSD
  arm production: the framework's own device decode chain (cross-checks
                  numpy flooding == production flooding)

All arms share the OSD-E(order 10) postprocess (decoders/osd.py) on their
BP-failed shots.  If serial arms move WER by ~the observed p_c offset
(~20%), decoder schedule is a live explanation; if not, it is eliminated.

Usage:
  JAX_PLATFORMS=cpu python scripts/ab_bp_schedule.py --d 5 --cycles 20 \
      --p 2e-3 --shots 20000
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# numpy normalized min-sum, flooding and check-serial schedules
def _check_supports(h):
    return [np.flatnonzero(h[c]).astype(np.int64) for c in range(h.shape[0])]


def _msgs_for_check(T, s_c, msf):
    """T: (B, w) extrinsic inputs for one check; returns (B, w) messages."""
    sgn = np.where(T < 0, -1.0, 1.0)
    parity = sgn.prod(axis=1) * (1.0 - 2.0 * s_c)  # (B,)
    absT = np.abs(T)
    # min excluding self: min1/min2 trick
    order = np.argsort(absT, axis=1)
    min1 = np.take_along_axis(absT, order[:, :1], 1)[:, 0]
    min2 = np.take_along_axis(absT, order[:, 1:2], 1)[:, 0]
    amin = np.where(absT == min1[:, None], min2[:, None], min1[:, None])
    # tie care: when several entries equal min1, excluding one still leaves
    # min1; the == test above handles only the argmin — fix via count
    ties = (absT == min1[:, None]).sum(1) > 1
    amin = np.where(ties[:, None], min1[:, None], amin)
    return msf * parity[:, None] * sgn * amin


def bp_numpy(h, synd, llr0, max_iter, msf=0.625, schedule="flood"):
    """Returns (error, converged, posterior_llr)."""
    m, n = h.shape
    B = synd.shape[0]
    sup = _check_supports(h)
    s = synd.astype(np.float64)
    M = np.zeros((B, m, n), np.float64)
    L = np.broadcast_to(llr0, (B, n)).copy()
    e = np.zeros((B, n), np.uint8)
    conv = np.zeros(B, bool)
    for _ in range(max_iter):
        if schedule == "flood":
            newM = np.zeros_like(M)
            colsum = M.sum(1)                                  # (B, n)
            for c in range(m):
                S = sup[c]
                T = llr0[S] + colsum[:, S] - M[:, c, S]
                newM[:, c, S] = _msgs_for_check(T, s[:, c], msf)
            M = newM
            L = llr0 + M.sum(1)
        else:  # check-serial
            for c in range(m):
                S = sup[c]
                T = L[:, S] - M[:, c, S]
                new = _msgs_for_check(T, s[:, c], msf)
                L[:, S] += new - M[:, c, S]
                M[:, c, S] = new
        e = (L <= 0).astype(np.uint8)
        syn_hat = (e @ h.T) % 2
        conv = (syn_hat == synd).all(1)
        if conv.all():
            break
    return e, conv, L


def bposd_numpy(h, synd, llr0, channel_probs, max_iter, msf=0.625,
                schedule="flood", osd_order=10):
    from qldpc_fault_tolerance_tpu.decoders.osd import osd_postprocess

    e, conv, L = bp_numpy(h, synd, llr0, max_iter, msf, schedule)
    return osd_postprocess(h, synd, e, conv, L, channel_probs,
                           osd_method="osd_e", osd_order=osd_order)


# ---------------------------------------------------------------------------
def run_chain(code, dets, obs, cycles, p, dec1_schedule, dec2_schedule,
              chunk=5000):
    """The reference's per-round residual feed-forward chain
    (src/Simulators.py:612-641) in numpy, with selectable BP schedules."""
    hx = code.hx.astype(np.uint8)
    m, N = hx.shape
    ext = np.hstack([hx, np.eye(m, dtype=np.uint8)])
    p_data = 3 * 6 * (8 / 15) * p
    p_synd = 7 * (8 / 15) * p
    probs1 = np.hstack([np.full(N, p_data), np.full(m, p_synd)])
    llr1 = np.log((1 - probs1) / probs1)
    probs2 = np.full(N, p)
    llr2 = np.log((1 - probs2) / probs2)
    mi1 = max(1, int(N / 30))
    mi2 = int(N / 10)
    lx = code.lx.astype(np.uint8)
    B = dets.shape[0]
    fails = np.zeros(B, bool)
    for i0 in range(0, B, chunk):
        d = dets[i0:i0 + chunk]
        o = obs[i0:i0 + chunk]
        b = d.shape[0]
        hist = d.reshape(b, cycles, m)
        correction = np.zeros((b, N), np.uint8)
        residual = np.zeros((b, m), np.uint8)
        for j in range(cycles - 1):
            corrected = hist[:, j] ^ residual
            e1, _, _ = bp_numpy(ext, corrected, llr1, mi1,
                                schedule=dec1_schedule)
            data_cor = e1[:, :N]
            correction ^= data_cor
            residual = (corrected ^ (data_cor @ hx.T % 2)).astype(np.uint8)
        corrected_final = hist[:, -1] ^ residual
        final_cor = bposd_numpy(hx, corrected_final, llr2, probs2, mi2,
                                schedule=dec2_schedule)
        total = correction ^ final_cor
        res_syn = corrected_final ^ (final_cor @ hx.T % 2).astype(np.uint8)
        log_cor = (total @ lx.T % 2).astype(np.uint8)
        res_log = o ^ log_cor
        fails[i0:i0 + chunk] = res_syn.any(1) | res_log.any(1)
    return int(fails.sum())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--d", type=int, default=5)
    ap.add_argument("--cycles", type=int, default=20)
    ap.add_argument("--p", type=float, default=2e-3)
    ap.add_argument("--shots", type=int, default=20000)
    args = ap.parse_args()

    from ab_frame_sim import NaiveFrameSim, build_toric_circuit

    code, circ = build_toric_circuit(args.d, args.cycles, args.p)
    naive = NaiveFrameSim(circ)
    rng = np.random.default_rng(42)
    parts = [naive.run(min(10000, args.shots - i), rng)
             for i in range(0, args.shots, 10000)]
    dets = np.concatenate([x[0] for x in parts])
    obs = np.concatenate([x[1] for x in parts])
    print(f"toric d{args.d} cycles={args.cycles} p={args.p} "
          f"shots={args.shots} (one fixed sample set for all arms)")

    for name, s1, s2 in (("flood", "flood", "flood"),
                         ("serial1", "serial", "flood"),
                         ("serial_all", "serial", "serial")):
        f = run_chain(code, dets, obs, args.cycles, args.p, s1, s2)
        print(f"arm {name:11s}: failures {f:6d}  rate {f / args.shots:.5f}")

    # production arm: same dets through the framework's device chain
    import jax.numpy as jnp

    from parity import make_circuit_decoders
    from qldpc_fault_tolerance_tpu.sim import CodeSimulator_Circuit
    from qldpc_fault_tolerance_tpu.sim.circuit import _decode_rounds_given

    error_params = {"p_i": 0, "p_state_p": 0, "p_m": 0, "p_CX": args.p,
                    "p_idling_gate": 0}
    dec1, dec2 = make_circuit_decoders(code, args.p)
    sim = CodeSimulator_Circuit(code=code, decoder1_z=dec1, decoder2_z=dec2,
                                p=args.p, num_cycles=args.cycles,
                                error_params=error_params, seed=0)
    sim._generate_circuit()
    f_prod = 0
    for i in range(0, args.shots, 5000):
        b = min(5000, args.shots - i)
        pending = _decode_rounds_given(
            sim._cfg(b), sim._dev_state,
            jnp.asarray(dets[i:i + b]), jnp.asarray(obs[i:i + b]))
        f_prod += int(np.asarray(sim._finish_batch(pending)).sum())
    print(f"arm production : failures {f_prod:6d}  "
          f"rate {f_prod / args.shots:.5f}")


if __name__ == "__main__":
    main()
