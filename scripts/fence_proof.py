"""Prove the tunneled-worker crash configs are worker bugs, not framework
limits: run the SAME shapes at FULL batch on the CPU backend.

Config A — bench.py's bposd mode (hgp_34_n625 data-error BP+OSD) at batch
8192, i.e. twice the axon worker's crash threshold (>= 4096).
Config B — an hgp_34_n1600 phenomenological parity cell (Threshold ckpt
cell 12 recipe: [H|I] dec1 int(N/30) iters, BPOSD osd_e order-10 dec2
int(N/10) iters, q=0) — the exact per-cell program that crashes the worker
at ANY batch.

Writes FENCE_PROOF.json.  Run with JAX_PLATFORMS=cpu (the point is the CPU
backend); wall-clock is minutes — this is a proof artifact, not a bench.
"""
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

from qldpc_fault_tolerance_tpu.codes import load_code  # noqa: E402
from qldpc_fault_tolerance_tpu.decoders import BPOSD_Decoder  # noqa: E402
from qldpc_fault_tolerance_tpu.sim import CodeSimulator_DataError  # noqa: E402

import parity  # noqa: E402


def main():
    # The proof is only a proof on the CPU backend.  The old guard
    # (``backend != 'axon'``) passed on the tunneled worker — which reports
    # 'tpu' — so a committed FENCE_PROOF.json could claim "runs fine off the
    # worker" while having run ON it (that artifact shipped mislabeled with
    # backend 'tpu' through round 5; regenerated on CPU this round).
    assert jax.default_backend() == "cpu", (
        f"run me with JAX_PLATFORMS=cpu (got backend "
        f"{jax.default_backend()!r}) — the point is the non-worker backend")
    out = {"backend": jax.default_backend(), "results": {}}

    # ---- config A: BP+OSD at batch 8192 (worker crashes at >= 4096)
    code = load_code(os.path.join(REPO, "codes_lib_tpu", "hgp_34_n625.npz"))
    p = 0.01
    dec = lambda h: BPOSD_Decoder(  # noqa: E731
        h, np.full(code.N, p), max_iter=50, bp_method="minimum_sum",
        ms_scaling_factor=0.625, osd_method="osd_e", osd_order=10)
    sim = CodeSimulator_DataError(
        code=code, decoder_x=dec(code.hz), decoder_z=dec(code.hx),
        pauli_error_probs=[p / 3] * 3, batch_size=8192, seed=11,
    )
    t0 = time.time()
    wer, eb = sim.WordErrorRate(16384)
    out["results"]["bposd_batch8192_n625"] = {
        "batch_size": 8192, "shots": 16384, "wer": float(wer),
        "eb": float(eb), "elapsed_s": round(time.time() - t0, 1),
        "ok": bool(0.0 <= wer <= 1.0),
    }
    print(out["results"]["bposd_batch8192_n625"])

    # ---- config B: n1600 phenl cell (crashes the worker at any batch)
    code = load_code(os.path.join(REPO, "codes_lib_tpu", "hgp_34_n1600.npz"))
    t0 = time.time()
    w = parity.phenl_cell_wer(code, eval_p=0.02, cycles=6, samples=2048,
                              seed=1, batch_size=2048)
    out["results"]["phenl_n1600_cell"] = {
        "batch_size": 2048, "samples": 2048, "cycles": 6, "p": 0.02,
        "wer_per_cycle": float(w), "elapsed_s": round(time.time() - t0, 1),
        "ok": bool(0.0 <= w <= 1.0),
    }
    print(out["results"]["phenl_n1600_cell"])

    with open(os.path.join(REPO, "FENCE_PROOF.json"), "w") as f:
        json.dump(out, f, indent=1)
    print("wrote FENCE_PROOF.json")


if __name__ == "__main__":
    main()
