"""Generate the VMEM calibration table (calibration/vmem_table.json).

For every shipped code shape (codes_lib_tpu/*.npz plus small HGP shapes)
and every VMEM-gated Pallas kernel — the v1/v2 BP heads (ops/bp_pallas),
the fused GF(2) sample/residual/whole-pipeline kernels (ops/gf2_pallas)
and the OSD-CS combination sweep (ops/osd_cs_device) — the harness:

  1. records the ANALYTIC per-shot / per-block VMEM estimate (the numbers
     the gates used through round 5, known to undercount mosaic
     temporaries ~1.8x at n1225 — README "Known frontiers");
  2. probes the LARGEST WORKING block via try-compile
     (utils.profiling.probe_max_block): on TPU each candidate block is
     lowered and compiled for real, so a scoped-VMEM OOM is data, not a
     crash; on CPU (no mosaic) the probe validates lowering in interpret
     mode and the feasibility criterion falls back to the analytic budget
     — entries are marked ``"measured": false`` so consumers know the
     ratio is a prior, not evidence;
  3. writes everything into one JSON table consumed by the gates
     (``bp_pallas.PallasHeadGraph.per_shot_bytes`` / ``fits_vmem`` and
     ``gf2_pallas.vmem_feasible`` via ``utils.profiling.vmem_table``).

Usage:
    python scripts/vmem_calibrate.py [--out calibration/vmem_table.json]
                                     [--codes hgp_34_n625 ...] [--quick]
                                     [--incremental]

``--incremental`` reads the existing table at ``--out`` and re-probes only
the (kernel, code) pairs whose fingerprint — jaxlib version, backend,
probe batch and hx shape — changed since that table was generated;
unchanged entries are carried over verbatim.  Upgrading jaxlib, switching
backend, or editing a code's check matrix each invalidate exactly the
entries they affect.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TABLE_SCHEMA = 1


def _on_tpu() -> bool:
    import jax

    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _code_shapes(names):
    """(name, hx, hz, lx, lz) per requested code: codes_lib_tpu npz files
    plus always-available small HGP shapes for quick runs."""
    import numpy as np

    from qldpc_fault_tolerance_tpu.codes import hgp, load_code, rep_code

    out = []
    for name in names:
        path = os.path.join(REPO, "codes_lib_tpu", f"{name}.npz")
        if os.path.exists(path):
            c = load_code(path)
            out.append((name, np.asarray(c.hx), np.asarray(c.hz),
                        np.asarray(c.lx), np.asarray(c.lz)))
            continue
        if name.startswith("hgp_rep"):
            d = int(name[len("hgp_rep"):])
            c = hgp(rep_code(d), rep_code(d), name=name)
            out.append((name, np.asarray(c.hx), np.asarray(c.hz),
                        np.asarray(c.lx), np.asarray(c.lz)))
            continue
        print(f"warning: unknown code {name!r}, skipped", file=sys.stderr)
    return out


def entry_fingerprint(kernel: str, code: str, hx, backend: str,
                      batch: int) -> str:
    """Identity of one calibration probe: anything that can change its
    outcome.  jaxlib carries the mosaic compiler version; the hx shape
    stands in for the code's check matrix (codes_lib_tpu codes are
    immutable per name+shape)."""
    import jaxlib.version

    from qldpc_fault_tolerance_tpu.utils.diagnostics import config_signature

    return config_signature({
        "kernel": kernel,
        "code": code,
        "jaxlib": jaxlib.version.__version__,
        "backend": backend,
        "probe_batch": batch,
        "hx_shape": list(getattr(hx, "shape", ())),
    })


def _bp_head_probe(hx, on_tpu: bool, batch: int):
    """One bp_head calibration entry: analytic per-shot estimate + the
    probed max block.  The try-compile callback lowers+compiles the real
    kernel per candidate on TPU (interpret-mode lowering on CPU)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from qldpc_fault_tolerance_tpu.ops import bp, bp_pallas
    from qldpc_fault_tolerance_tpu.utils import profiling

    graph = bp.build_tanner_graph_host(hx) \
        if hasattr(bp, "build_tanner_graph_host") else bp.build_tanner_graph(hx)
    pg = bp_pallas.build_pallas_head(graph)
    m, n, rw = pg.m, pg.n, pg.rw
    analytic = pg.analytic_per_shot_bytes
    llr0 = bp.llr_from_probs(np.full(n, 0.01))
    synd = jnp.zeros((batch, m), jnp.uint8)

    def try_compile(block_b: int) -> bool:
        if batch % block_b:
            return False
        if not on_tpu:
            # no mosaic on CPU: validate lowering in interpret mode, gate
            # feasibility on the analytic budget (recorded as a prior)
            bp_pallas.bp_head_pallas.lower(
                pg, synd, llr0, head_iters=3, block_b=block_b,
                interpret=True)
            return block_b * analytic <= 30 * 1024 * 1024 - pg.scat_bytes
        bp_pallas.bp_head_pallas.lower(
            pg, synd, llr0, head_iters=3, block_b=block_b).compile()
        return True

    candidates = [bt for bt in (512, 256, 128, 64, 32, 16, 8)
                  if bt <= batch]
    best, attempts = profiling.probe_max_block(try_compile, candidates)
    entry = {
        "kernel": "bp_head", "rw": rw, "m": m, "n": n,
        "scat_bytes": pg.scat_bytes,
        "analytic_per_shot_bytes": analytic,
        "probe_batch": batch,
        "max_block_b": best,
        "measured": bool(on_tpu),
        "attempts": [{"block": b, "ok": ok, **({"error": e} if e else {})}
                     for b, ok, e in attempts],
    }
    if best:
        # per-shot budget implied by the probe: the largest working block
        # saturates (budget / per_shot), so the measured per-shot bytes
        # are at most budget/best.  Only a TPU probe is mosaic evidence —
        # it lands in ``per_shot_bytes``, the key the gates consume
        # (profiling.calibrated_per_shot_bytes additionally requires
        # ``measured``); the CPU run records the same number under an
        # informational name so the table documents the probe grid
        # without overriding the analytic estimator.
        budget = 30 * 1024 * 1024 - pg.scat_bytes
        if on_tpu:
            entry["per_shot_bytes"] = round(budget / best, 1)
            entry["ratio_vs_analytic"] = round(budget / best / analytic, 3)
        else:
            # probe-grid upper bound only (the analytic gate restated at
            # the coarse candidate grid) — informational, never consumed
            entry["implied_per_shot_bytes_upper"] = round(budget / best, 1)
    return entry


def _bp_head_v2_probe(hx, on_tpu: bool, batch: int):
    """Calibration entries for the v2 sparse-incidence head: the fixed
    (index + synthesized-one-hot) overhead plus the probed per-shot
    budget, with an int8-variant lowering check at the best block."""
    import jax.numpy as jnp
    import numpy as np

    from qldpc_fault_tolerance_tpu.ops import bp, bp_pallas
    from qldpc_fault_tolerance_tpu.utils import profiling

    graph = bp.build_tanner_graph_host(hx)
    sg = bp_pallas.build_sparse_head(graph)
    m, n, rw = sg.m, sg.n, sg.rw
    analytic = sg.analytic_per_shot_bytes
    budget = 30 * 1024 * 1024 - sg.fixed_overhead_bytes
    llr0 = bp.llr_from_probs(np.full(n, 0.01))
    synd = jnp.zeros((batch, m), jnp.uint8)

    def lower(block_b, quantize):
        return bp_pallas._bp_head_sparse_pallas.lower(
            sg, synd, llr0, head_iters=3, ms_scaling_factor=0.625,
            block_b=block_b, interpret=not on_tpu, early_stop=False,
            quantize=quantize)

    def try_compile(block_b: int) -> bool:
        if batch % block_b:
            return False
        if not on_tpu:
            lower(block_b, None)
            return block_b * analytic <= budget
        lower(block_b, None).compile()
        return True

    candidates = [bt for bt in (512, 256, 128, 64, 32, 16, 8)
                  if bt <= batch]
    best, attempts = profiling.probe_max_block(try_compile, candidates)
    entry = {
        "kernel": "bp_head_v2", "rw": rw, "m": m, "n": n,
        "fixed_overhead_bytes": sg.fixed_overhead_bytes,
        "analytic_per_shot_bytes": analytic,
        "probe_batch": batch,
        "max_block_b": best,
        "measured": bool(on_tpu),
        "attempts": [{"block": b, "ok": ok, **({"error": e} if e else {})}
                     for b, ok, e in attempts],
    }
    if best:
        if on_tpu:
            entry["per_shot_bytes"] = round(budget / best, 1)
            entry["ratio_vs_analytic"] = round(budget / best / analytic, 3)
        else:
            entry["implied_per_shot_bytes_upper"] = round(budget / best, 1)
        # the int8 variant shares the estimator; record that it lowers
        # (and on TPU, compiles) at the probed block
        try:
            lowered = lower(best, "int8")
            if on_tpu:
                lowered.compile()
            entry["int8_ok"] = True
        except Exception as e:
            entry["int8_ok"] = False
            entry["int8_error"] = f"{type(e).__name__}: {e}"[:200]
    return entry


def _fused_decode_probe(name, hx, hz, lx, lz, on_tpu: bool, batch: int):
    """Calibration entry for the whole-pipeline fused v2 program."""
    import jax
    import numpy as np

    from qldpc_fault_tolerance_tpu.ops import bp, gf2_pallas
    from qldpc_fault_tolerance_tpu.ops.gf2_packed import LANE
    from qldpc_fault_tolerance_tpu.utils import profiling

    n = hx.shape[1]
    llr = bp.llr_from_probs(np.full(n, 0.01))
    spec2 = gf2_pallas.build_fused_decode_spec(
        hx, hz, lx, lz, (0.003,) * 3, llr, llr)
    d = gf2_pallas._decode_statics(spec2)
    key = jax.random.PRNGKey(0)

    def try_compile(block_w: int) -> bool:
        if batch % (block_w * LANE):
            return False
        lowered = gf2_pallas._fused_decode_pallas.lower(
            spec2, key, batch, "Total", 3, 3, 0.625, None, block_w,
            not on_tpu)
        if on_tpu:
            lowered.compile()
            return True
        return gf2_pallas.estimate_fused_decode_bytes(
            d["n"], d["mx"], d["mz"], d["rwz"], d["rwx"], block_w
        ) <= gf2_pallas._KERNEL_VMEM_LIMIT

    candidates = [bw for bw in (8, 4, 2, 1) if bw * LANE <= batch]
    best, attempts = profiling.probe_max_block(try_compile, candidates)
    analytic = gf2_pallas.estimate_fused_decode_bytes(
        d["n"], d["mx"], d["mz"], d["rwz"], d["rwx"], 4) / 2.0
    entry = {
        "kernel": "fused_decode", "n": d["n"], "mx": d["mx"], "mz": d["mz"],
        "analytic_block_bytes": round(analytic, 1),
        "probe_batch": batch,
        "max_block_w": best,
        "measured": bool(on_tpu),
        "attempts": [{"block": b, "ok": ok, **({"error": e} if e else {})}
                     for b, ok, e in attempts],
    }
    if on_tpu and best:
        raw = gf2_pallas.estimate_fused_decode_bytes(
            d["n"], d["mx"], d["mz"], d["rwz"], d["rwx"], best) / 2.0
        entry["ratio_vs_analytic"] = round(
            gf2_pallas._KERNEL_VMEM_LIMIT / raw, 3)
    return entry


def _gf2_probe(name, hx, hz, lx, lz, on_tpu: bool, batch: int):
    """Calibration entries for the fused sample/residual kernels."""
    import jax.numpy as jnp

    from qldpc_fault_tolerance_tpu.ops import gf2_pallas
    from qldpc_fault_tolerance_tpu.ops.gf2_packed import LANE, num_words
    from qldpc_fault_tolerance_tpu.utils import profiling

    import jax

    spec = gf2_pallas.build_fused_spec(hx, hz, lx, lz, (0.003,) * 3)
    n, mx = spec.hx_t.shape
    mz = spec.hz_t.shape[1]
    key = jax.random.PRNGKey(0)
    entries = []
    for kernel, fn in (
        ("gf2_sample_synd",
         lambda bw: gf2_pallas._sample_syndrome_pallas.lower(
             spec, key, batch, bw, not on_tpu, True)),
        ("gf2_residual",
         lambda bw: gf2_pallas._residual_check_pallas.lower(
             spec, key, batch,
             jnp.zeros((num_words(batch), n), jnp.uint32),
             jnp.zeros((num_words(batch), n), jnp.uint32),
             "Total", bw, not on_tpu)),
    ):
        def try_compile(block_w: int, fn=fn, kernel=kernel) -> bool:
            if batch % (block_w * LANE):
                return False
            lowered = fn(block_w)
            if on_tpu:
                lowered.compile()
                return True
            est = gf2_pallas.estimate_vmem_bytes(n, mx, mz, block_w,
                                                 kernel=kernel)
            return est <= gf2_pallas._KERNEL_VMEM_LIMIT

        candidates = [bw for bw in (64, 32, 16, 8, 4, 2, 1)
                      if bw * LANE <= batch]
        best, attempts = profiling.probe_max_block(try_compile, candidates)
        analytic = gf2_pallas.estimate_vmem_bytes(
            n, mx, mz, gf2_pallas._DEFAULT_BLOCK_W, kernel=kernel) / 2.0
        entry = {
            "kernel": kernel, "n": n, "mx": mx, "mz": mz,
            "analytic_block_bytes": round(analytic, 1),
            "probe_batch": batch,
            "max_block_w": best,
            "measured": bool(on_tpu),
            "attempts": [{"block": b, "ok": ok,
                          **({"error": e} if e else {})}
                         for b, ok, e in attempts],
        }
        if on_tpu and best:
            # the largest compiling block saturates the scoped cap, so the
            # true working set at ``best`` is at most the cap: the implied
            # measured/analytic ratio feeds table['ratios'] — the factor
            # gf2_pallas.estimate_vmem_bytes consumes (its 2.0 default is
            # the uncalibrated prior)
            raw = gf2_pallas.estimate_vmem_bytes(
                n, mx, mz, best, kernel=kernel) / 2.0
            entry["ratio_vs_analytic"] = round(
                gf2_pallas._KERNEL_VMEM_LIMIT / raw, 3)
        entries.append(entry)
    return entries


def _osd_cs_probe(name, hx, on_tpu: bool, batch: int):
    """Calibration entry for the OSD-CS combination sweep (ISSUE 19): the
    pattern-chunk chooser and residency gate restated at this code's
    (n, rank) with osd_order=10, plus a probe of the sweep at candidate
    chunk sizes — real compiles on TPU, interpret execution on CPU with
    feasibility falling back to the analytic residency budget (entries
    stay ``"measured": false`` off-TPU, same contract as the BP probes)."""
    import jax.numpy as jnp
    import numpy as np

    from qldpc_fault_tolerance_tpu.ops import osd_cs_device as cs
    from qldpc_fault_tolerance_tpu.ops.osd_device import build_osd_plan
    from qldpc_fault_tolerance_tpu.utils import profiling

    order = 10
    bt = 128
    plan = build_osd_plan(hx, np.full(hx.shape[1], 0.01))
    n, rank = plan.n, plan.rank
    f, w, _ = cs._cs_counts(n, rank, order)
    n_cand, n_chunks = cs.cs_sweep_shape(n, rank, order)
    chosen = cs.cs_pat_chunk(n, rank, order, bt)
    wsq = max(w * w, 1)
    fcols = max(f, 1)
    limit = cs._gate("osd_cs_sweep_limit_bytes", cs._CS_SWEEP_VMEM_LIMIT)

    def sweep_bytes(chunk: int) -> int:
        n_pad = -(-n_cand // chunk) * chunk
        return 4 * (n_pad * fcols + n_pad * wsq + (fcols + wsq + 8) * bt
                    + chunk * bt + 2 * 8 * bt)

    def try_compile(chunk: int) -> bool:
        e1t, e2t, _j1, _j2, _nc, _np_ = cs._cs_plane(f, w, chunk)
        dplane = jnp.zeros((fcols, bt), jnp.float32)
        xflat = jnp.zeros((wsq, bt), jnp.float32)
        base = jnp.zeros((bt,), jnp.float32)
        cs._cs_sweep_pallas(jnp.asarray(e1t), jnp.asarray(e2t), dplane,
                            xflat, base, chunk, bt=bt,
                            interpret=not on_tpu)
        if not on_tpu:
            # no mosaic on CPU: interpret execution validates lowering,
            # feasibility falls back to the analytic residency budget
            return sweep_bytes(chunk) <= limit
        return True

    candidates = [c for c in (512, 256, 128, 64) if c <= max(n_cand, 64)]
    best, attempts = profiling.probe_max_block(try_compile, candidates)
    entry = {
        "kernel": "osd_cs_sweep", "n": n, "rank": rank, "f": f, "w": w,
        "osd_order": order, "n_candidates": n_cand, "n_chunks": n_chunks,
        "chosen_pat_chunk": chosen,
        "analytic_sweep_bytes": sweep_bytes(chosen),
        "feasible": cs.cs_sweep_feasible(n, rank, order, bt),
        "probe_bt": bt,
        "max_pat_chunk": best,
        "measured": bool(on_tpu),
        "attempts": [{"block": b, "ok": ok, **({"error": e} if e else {})}
                     for b, ok, e in attempts],
    }
    return entry


def build_table(code_names, quick: bool = False, prev: dict | None = None,
                ) -> dict:
    import jax

    on_tpu = _on_tpu()
    backend = jax.default_backend()
    batch = 1024 if quick else 4096
    prev_entries = {}
    if prev and prev.get("schema") == TABLE_SCHEMA:
        prev_entries = {(e.get("kernel"), e.get("code")): e
                        for e in prev.get("entries", [])
                        if e.get("fingerprint")}
    entries = []
    reused = probed = 0
    # each probe group re-runs as a unit; _gf2_probe emits two kernels
    groups = (
        (("bp_head",),
         lambda name, hx, hz, lx, lz: [_bp_head_probe(hx, on_tpu, batch)]),
        (("bp_head_v2",),
         lambda name, hx, hz, lx, lz: [_bp_head_v2_probe(hx, on_tpu,
                                                         batch)]),
        (("fused_decode",),
         lambda name, hx, hz, lx, lz: [_fused_decode_probe(
             name, hx, hz, lx, lz, on_tpu, batch)]),
        (("osd_cs_sweep",),
         lambda name, hx, hz, lx, lz: [_osd_cs_probe(name, hx, on_tpu,
                                                     batch)]),
        (("gf2_sample_synd", "gf2_residual"),
         lambda name, hx, hz, lx, lz: _gf2_probe(name, hx, hz, lx, lz,
                                                 on_tpu, batch)),
    )
    for name, hx, hz, lx, lz in _code_shapes(code_names):
        for kernels, probe in groups:
            fps = {k: entry_fingerprint(k, name, hx, backend, batch)
                   for k in kernels}
            carried = [prev_entries[(k, name)] for k in kernels
                       if (k, name) in prev_entries
                       and prev_entries[(k, name)]["fingerprint"] == fps[k]]
            if len(carried) == len(kernels):
                entries.extend(dict(e) for e in carried)
                reused += len(carried)
                continue
            print(f"probing {name} (hx {hx.shape}): "
                  f"{'/'.join(kernels)}...", file=sys.stderr)
            for e in probe(name, hx, hz, lx, lz):
                e["code"] = name
                e["fingerprint"] = fps[e["kernel"]]
                entries.append(e)
                probed += 1
    if prev_entries:
        print(f"incremental: {reused} entries reused, {probed} re-probed",
              file=sys.stderr)
    # kernel-wide measured/analytic ratios: only TPU probes are evidence;
    # the 1.8x bp_head prior comes from the round-4 n1225 measurement
    # (README "Known frontiers") and stands until a TPU run replaces it
    ratios = {}
    for kernel in ("bp_head", "bp_head_v2", "fused_decode",
                   "gf2_sample_synd", "gf2_residual", "osd_cs_sweep"):
        rs = [e["ratio_vs_analytic"] for e in entries
              if e["kernel"] == kernel and e.get("measured")
              and e.get("ratio_vs_analytic")]
        if rs:
            ratios[kernel] = round(max(rs), 3)
    if "bp_head" not in ratios:
        ratios["bp_head_prior"] = 1.8

    from qldpc_fault_tolerance_tpu.ops import bp_pallas

    # explicit gate values: the CONSUMED keys always exist in a generated
    # table so consumers (and the tier-1 consistency test) never depend on
    # fallback constants silently; a CPU run records the conservative
    # defaults (gates_measured=false), a TPU run may raise them with
    # try-compile evidence
    from qldpc_fault_tolerance_tpu.ops import osd_cs_device

    gates = {
        "bp_head_scat_limit_bytes": 8 * 1024 * 1024,
        "bp_head_v2_fixed_limit_bytes": bp_pallas._V2_FIXED_LIMIT,
        # OSD-CS sweep (ISSUE 19): conservative shipped defaults — a TPU
        # calibration run may raise them with try-compile evidence
        "osd_cs_sweep_limit_bytes": osd_cs_device._CS_SWEEP_VMEM_LIMIT,
        "osd_cs_chunk_limit_bytes": osd_cs_device._CS_CHUNK_LIMIT,
    }

    return {
        "schema": TABLE_SCHEMA,
        "generated_by": "scripts/vmem_calibrate.py",
        "generated_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "backend": jax.default_backend(),
        "measured": on_tpu,
        "probe_batch": batch,
        "ratios": ratios,
        "gates": gates,
        "gates_measured": on_tpu,
        "entries": entries,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=os.path.join(
        REPO, "calibration", "vmem_table.json"))
    ap.add_argument("--codes", nargs="*", default=[
        "hgp_rep3", "hgp_rep5", "hgp_34_n225", "hgp_34_n625",
        "hgp_34_n1225", "hgp_34_n1600"])
    ap.add_argument("--quick", action="store_true",
                    help="smaller probe batch (faster, coarser)")
    ap.add_argument("--incremental", action="store_true",
                    help="reuse entries from the existing --out table "
                         "whose fingerprint (jaxlib/backend/batch/shape) "
                         "is unchanged; re-probe only the rest")
    args = ap.parse_args(argv)

    prev = None
    if args.incremental and os.path.exists(args.out):
        try:
            with open(args.out, encoding="utf-8") as fh:
                prev = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"warning: could not read previous table ({e}); "
                  f"full re-probe", file=sys.stderr)

    table = build_table(args.codes, quick=args.quick, prev=prev)
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(table, fh, indent=1, sort_keys=False)
        fh.write("\n")
    print(f"wrote {args.out}: {len(table['entries'])} entries "
          f"(backend {table['backend']}, measured={table['measured']})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
