#!/bin/bash
# Round-5 LP/GBC parity campaign (VERDICT item 1). Sequential to keep the
# single tunneled chip uncontended. Each experiment gets up to 3 process-level
# attempts (the in-process retry already handles worker crashes; a process
# retry covers compile-helper sickness that outlives it).
cd /root/repo
run() {
  for attempt in 1 2 3; do
    echo "=== $(date +%H:%M:%S) $* (attempt $attempt) ==="
    python scripts/parity.py "$@" && return 0
    echo "--- experiment $1 attempt $attempt failed (rc $?); cooling 120s"
    sleep 120
  done
  echo "!!! experiment $1 exhausted attempts"
  return 1
}
run lp_phenl_12k --seeds 2 --warmup
run gbc_circuit  --seeds 2 --warmup
run lp_circuit   --seeds 2 --warmup
run lp_phenl     --seeds 2 --warmup
echo "CAMPAIGN_DONE"
