#!/usr/bin/env python
"""Run the fleet federation gateway over N serving hosts' ops endpoints.

    python scripts/fleet_gateway.py --port 9100 \
        --target a=http://10.0.0.1:9001 --target b=http://10.0.0.2:9001

Serves the merged fleet view (see qldpc_fault_tolerance_tpu.serve.fleet):
/metrics (counter sums bit-exact, histogram buckets additive, per-host
labels), /healthz (per-host up/down + aggregate), /alertz (union of host
alerts + host-down deadman), /varz (the merge inputs + skips).  Bare URLs
without ``label=`` get host0, host1, ... labels.
"""
from __future__ import annotations

import argparse
import sys
import threading


def parse_targets(specs) -> dict:
    targets = {}
    for i, spec in enumerate(specs):
        if "=" in spec.split("://", 1)[0]:
            label, url = spec.split("=", 1)
        else:
            label, url = f"host{i}", spec
        if label in targets:
            raise SystemExit(f"duplicate target label {label!r}")
        targets[label] = url
    return targets


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--target", action="append", default=[],
                    metavar="LABEL=URL", dest="targets",
                    help="ops endpoint to federate (repeatable)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--interval", type=float, default=5.0,
                    help="scrape interval, seconds")
    ap.add_argument("--down-after", type=float, default=None,
                    help="host-down deadman window (default 3 intervals)")
    ap.add_argument("--telemetry-jsonl", default=None,
                    help="enable telemetry with this JSONL sink (alert "
                         "transition events land there)")
    args = ap.parse_args(argv)
    if not args.targets:
        ap.error("at least one --target is required")

    from qldpc_fault_tolerance_tpu.serve import fleet
    from qldpc_fault_tolerance_tpu.utils import telemetry

    if args.telemetry_jsonl:
        telemetry.enable(args.telemetry_jsonl)
    gw = fleet.FleetGateway(parse_targets(args.targets),
                            interval_s=args.interval,
                            down_after_s=args.down_after)
    handle = fleet.start_fleet_thread(gw, host=args.host, port=args.port)
    host, port = handle.address
    print(f"fleet gateway on http://{host}:{port} "
          f"({len(gw.targets)} hosts, scrape every {args.interval:g}s) — "
          "/metrics /healthz /varz /alertz; Ctrl-C to stop")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        handle.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
