"""Consistency check for the msf-1.0 hypothesis (scripts/ab_iteration.py).

The msf=1.0 arm reproduces the published toric_circuit p_c at 20/25/30
cycles.  But the same `ldpc` binaries decode the phenl experiments, which
MATCH our msf=0.625 results — so the hypothesis survives only if the phenl
chain is msf-INsensitive (its window decodes see q=0 clean syndromes and
its final BPOSD sees iid data noise at 5-10x higher p).  This measures the
phenl WER under both msf values on the same error stream (same seed ->
identical sampled errors; only the decoders differ).

Usage: JAX_PLATFORMS=cpu python scripts/ab_msf_phenl.py
"""
from __future__ import annotations

import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    from qldpc_fault_tolerance_tpu.codes import hgp, ring_code
    from qldpc_fault_tolerance_tpu.decoders import BPDecoder, BPOSD_Decoder
    from qldpc_fault_tolerance_tpu.sim import CodeSimulator_Phenon

    p = 1.4e-2
    cycles = 20
    for d, shots in ((5, 40000), (9, 20000), (13, 10000)):
        code = hgp(ring_code(d), ring_code(d), name=f"toric_d{d}")
        pauli = [p / 3] * 3
        two_thirds = pauli[0] + pauli[1]
        m = code.hx.shape[0]
        ext_x = np.hstack([code.hz, np.eye(code.hz.shape[0], dtype=np.uint8)])
        ext_z = np.hstack([code.hx, np.eye(m, dtype=np.uint8)])
        for msf in (0.625, 1.0):
            kw = dict(bp_method="minimum_sum", ms_scaling_factor=msf)
            dec1_x = BPDecoder(ext_x, two_thirds * np.ones(ext_x.shape[1]),
                               max_iter=int(code.N / 30), **kw)
            dec1_z = BPDecoder(ext_z, two_thirds * np.ones(ext_z.shape[1]),
                               max_iter=int(code.N / 30), **kw)
            dec2_x = BPOSD_Decoder(code.hz, two_thirds * np.ones(code.N),
                                   max_iter=int(code.N / 10),
                                   osd_method="osd_e", osd_order=10, **kw)
            dec2_z = BPOSD_Decoder(code.hx, two_thirds * np.ones(code.N),
                                   max_iter=int(code.N / 10),
                                   osd_method="osd_e", osd_order=10, **kw)
            sim = CodeSimulator_Phenon(
                code=code, decoder1_x=dec1_x, decoder1_z=dec1_z,
                decoder2_x=dec2_x, decoder2_z=dec2_z,
                pauli_error_probs=pauli, q=0, seed=77, batch_size=2000,
            )
            count, total = sim._count_failures(cycles, shots)
            print(f"d{d:<2d} msf={msf}: {count:5d}/{total} = "
                  f"{count / total:.5f}", flush=True)


if __name__ == "__main__":
    main()
