"""Summarize PARITY_results.jsonl into PARITY_r2.md.

Groups runs by (experiment, cycles), reports the measured p_c per seed, the
seed spread, and the published reference value, and flags each row:
  MATCH    published value inside [min, max] of our seeds (or within 15% of
           the seed mean when all seeds agree tightly)
  NOISY    our own seeds disagree by >2x — the two-stage notebook fit is
           ill-conditioned at this operating point, for us and for the
           reference's single-seed published number alike
  MISMATCH seeds agree tightly with each other but not with the published
           value

Usage: python scripts/parity_report.py [--out PARITY_r2.md]
"""
import argparse
import json
import os
from collections import defaultdict

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# experiments whose code families are not byte-identical to the reference's:
# the hgp_34 n625/n1225/n1600 pickles are absent from the mount
# (.MISSING_LARGE_BLOBS), so those members are statistically-equivalent
# regenerations — with girth-6 seeds, whereas the reference's own shipped
# n225 seed has girth 4.  Better-conditioned Tanner graphs decode better,
# so a somewhat higher fitted p_c is the *expected* direction, not a bug.
_REGENERATED_FAMILY = {"hgp_phenl", "hgp_circuit"}


def classify(pcs, published, experiment=""):
    lo, hi = min(pcs), max(pcs)
    mean = float(np.mean(pcs))
    if hi > 2 * lo:
        return "NOISY"
    if published is None:
        return "-"
    if lo * 0.85 <= published <= hi * 1.15:
        return "MATCH"
    if abs(published - mean) <= 0.15 * mean:
        return "MATCH"
    if experiment in _REGENERATED_FAMILY:
        return "REGEN-DIFF"
    return "MISMATCH"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--results", default=os.path.join(REPO, "PARITY_results.jsonl"))
    ap.add_argument("--out", default=os.path.join(REPO, "PARITY_r2.md"))
    args = ap.parse_args()

    groups = defaultdict(list)
    for line in open(args.results):
        r = json.loads(line)
        groups[(r["experiment"], r["cycles"])].append(r)

    lines = [
        "# Physics parity vs the reference's published numbers (round 2)",
        "",
        "Each experiment replays a Threshold-checkpoint cell exactly — same",
        "codes, p-grid, decoder settings (incl. the notebook's q=0 quirk and",
        "even cycle counts), and the notebook's own two-stage ThresholdEst",
        "fit (per-code log-log distance fit, then joint EmpericalFit).",
        "Published values are single-seed notebook outputs; ours are run at",
        "multiple seeds so the fit variance is visible.  `scripts/parity.py`",
        "reproduces any row; raw per-cell WER grids are in",
        "PARITY_results.jsonl.",
        "",
        "| experiment | cycles | p_c per seed | published | verdict |",
        "|---|---|---|---|---|",
    ]
    verdicts = []
    for (exp, cycles), runs in sorted(groups.items()):
        # dedupe identical (seed) reruns, keep latest
        by_seed = {}
        for r in runs:
            by_seed[r["seed"]] = r
        pcs = [by_seed[s]["p_c"] for s in sorted(by_seed)]
        pcs_valid = [p for p in pcs if p == p]  # drop NaN (failed fits)
        published = runs[0].get("published_p_c")
        if not pcs_valid:
            v = "FIT-FAIL"
        elif len(pcs_valid) < len(pcs):
            # some seed's fit failed outright — the operating point is
            # fit-unstable, same class as wildly-spread seeds
            v = "NOISY"
        else:
            v = classify(pcs_valid, published, exp)
        verdicts.append(v)
        pcs_str = ", ".join(f"{p:.4f}" for p in pcs)
        pub_str = f"{published:.4f}" if published is not None else "-"
        lines.append(f"| {exp} | {cycles} | {pcs_str} | {pub_str} | {v} |")

    n_match = sum(v == "MATCH" for v in verdicts)
    n_noisy = sum(v in ("NOISY", "FIT-FAIL") for v in verdicts)
    n_regen = sum(v == "REGEN-DIFF" for v in verdicts)
    n_mis = sum(v == "MISMATCH" for v in verdicts)
    lines += [
        "",
        f"**{n_match} MATCH / {n_noisy} NOISY / {n_regen} REGEN-DIFF / "
        f"{n_mis} MISMATCH** across {len(verdicts)} published values.",
        "",
        "NOISY rows are operating points where our own independent seeds",
        "disagree by >2x at the reference's sample counts — the (p_c, A)",
        "joint fit is ill-conditioned there (the p-grid sits far below the",
        "crossing point, so A and p_c trade off freely).  The reference's",
        "single-seed published number at those points carries the same",
        "variance.",
        "",
        "REGEN-DIFF rows are the hgp_34 family experiments, which are not",
        "apples-to-apples: the n625/n1225/n1600 pickles are absent from the",
        "reference mount, so those members are [[N,K]]-matched",
        "regenerations with girth-6 seeds (the reference's own shipped n225",
        "seed has girth 4) — individual family members differ in effective",
        "distance, and the hgp circuit fits additionally extrapolate p_c",
        "up to 10x beyond the measured p-grid (the reference's cycles-3",
        "fit returns p_c=0.039 from a grid ending at 0.0035, A=2.6).  A",
        "low-p probe confirms our regenerated n1600 has no pathological",
        "error floor (WER -> 0 as p -> 0, ~p^1.5 scaling at 3 cycles).",
        "The toric experiments (identical codes by construction) are the",
        "apples-to-apples check.",
        "",
        "MISMATCH rows (toric_circuit cycles 25/30: our 4-seed means sit",
        "~20% above published with ~5% seed spread) trace to **CX-schedule",
        "sensitivity**, not decoder physics: rerunning cycles=25 with",
        "circuit_type='random' instead of 'coloration' moves our own p_c",
        "from 0.00296 to 0.00251 (-18%) — the same magnitude as the gap.",
        "Both schedulers emit valid syndrome-extraction circuits, but the",
        "exact edge-coloring depends on the matching order of the",
        "implementation (the reference's networkx Hopcroft-Karp vs our",
        "Konig construction), and the resulting error-propagation patterns",
        "differ increasingly with cycle count.  The toric_circuit cycles-6",
        "published value is a known fit outlier (BASELINE.md).",
        "",
        "## Direct-WER anchor (no fit)",
        "",
        "SpaceTimeDecodingDemo.ipynb cell 3 publishes a raw WER:",
        "1.930e-4 (toric d3, p_CX=1e-3, num_rep=3, 13 cycles, BP window +",
        "BPOSD final, 10k samples).  Executed unmodified through",
        "`compat.install()` (scripts/run_reference_notebook.py), this",
        "framework reproduces it within binomial error — see",
        "examples/executed/SpaceTimeDecodingDemo.executed.ipynb.",
    ]
    with open(args.out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {args.out}")
    print("\n".join(lines[-20:]))


if __name__ == "__main__":
    main()
