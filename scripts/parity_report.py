"""Summarize PARITY_results.jsonl into PARITY_r3.md.

Groups runs by (experiment, cycles, circuit_type), validates every fit, and
classifies each published value with a statistical rule:

  per-seed fit validation
      a seed's two-stage notebook fit is FIT-FAILED when it returns NaN, a
      non-positive/absurd amplitude, or a p_c outside [min(grid)/5,
      5*max(grid)] — curve_fit happily reports p_c = 2196 when the grid sits
      entirely below the crossing point; such numbers are flagged, never
      tabulated as measurements.

  row verdict (valid seeds only; mu = mean, sigma = std)
      FIT-UNSTABLE  fewer than 2 valid seeds
      NOISY         seeds spread >2x, or sigma > 0.3*mu — the fit is
                    ill-conditioned at this operating point, for us and for
                    the reference's single-seed published number alike
      MATCH         z = |published - mu| / max(sigma, 0.05*mu) <= 2
      REGEN-DIFF    z > 2 in an experiment whose code family is a
                    regeneration (reference pickles absent from the mount)
      MISMATCH      z > 2 with byte-identical codes

The z-floor of 0.05*mu guards the two-seed case where a lucky pair of
near-identical seeds would make sigma (and so the MATCH band) absurdly
small; it replaces round 2's +-15% interval rule, which let a 58% overshoot
pass through the slack on one seed.

Usage: python scripts/parity_report.py [--out PARITY_r3.md]
"""
import argparse
import json
import os
from collections import defaultdict

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# experiments whose code families are not byte-identical to the reference's:
# the hgp_34 n625/n1225/n1600 pickles are absent from the mount
# (.MISSING_LARGE_BLOBS), so those members are statistically-equivalent
# regenerations — with girth-6 seeds, whereas the reference's own shipped
# n225 seed has girth 4.  Better-conditioned Tanner graphs decode better,
# so a somewhat higher fitted p_c is the *expected* direction, not a bug.
_REGENERATED_FAMILY = {"hgp_phenl", "hgp_circuit"}


def fit_valid(rec):
    """Bound-check one seed's (p_c, A) against its own p-grid."""
    pc, a = rec.get("p_c"), rec.get("A")
    grid = rec.get("p_list") or []
    if pc is None or pc != pc or a is None or a != a:
        return False
    if not grid:
        return True
    return (min(grid) / 5 <= pc <= 5 * max(grid)) and (0 < a < 100)


def classify(pcs, published, experiment=""):
    mu = float(np.mean(pcs))
    sigma = float(np.std(pcs))
    if max(pcs) > 2 * min(pcs) or sigma > 0.3 * mu:
        return "NOISY", None
    if published is None:
        return "-", None
    z = abs(published - mu) / max(sigma, 0.05 * mu)
    if z <= 2:
        return "MATCH", z
    if experiment in _REGENERATED_FAMILY:
        return "REGEN-DIFF", z
    return "MISMATCH", z


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--results", default=os.path.join(REPO, "PARITY_results.jsonl"))
    ap.add_argument("--out", default=os.path.join(REPO, "PARITY_r3.md"))
    args = ap.parse_args()

    groups = defaultdict(list)
    for line in open(args.results):
        r = json.loads(line)
        if r.get("msf") not in (None, 0.625) or r.get("members"):
            # decoder-variant A/B rows (msf hypothesis) and 4-member d_eff
            # runs are analyzed separately (PARITY_r4.md), never mixed into
            # the published-comparison table
            continue
        sched = r.get("circuit_type") or "coloration"
        groups[(r["experiment"], r["cycles"], sched,
                float(r.get("p_scale") or 1.0))].append(r)

    lines = [
        "# Physics parity vs the reference's published numbers (round 3)",
        "",
        "Each experiment replays a Threshold-checkpoint cell exactly — same",
        "codes, p-grid, decoder settings (incl. the notebook's q=0 quirk and",
        "even cycle counts), and the notebook's own two-stage ThresholdEst",
        "fit.  Published values are single-seed notebook outputs; ours run at",
        "multiple seeds so fit variance is visible.  Verdicts use the",
        "z-score rule documented in scripts/parity_report.py (fits are",
        "bound-checked first; unphysical curve_fit outputs appear as FAIL,",
        "never as measurements).  `scripts/parity.py` reproduces any row;",
        "raw per-cell WER grids are in PARITY_results.jsonl.",
        "",
        "A direct-WER comparison against published per-cell grids is NOT",
        "possible: the checkpoint notebooks print only wall-clock and the",
        "fitted (A, p_c) per sweep — no raw WER arrays survive in any",
        "published output (verified against every Threshold/Single-Shot",
        "checkpoint cell).  The only published direct-WER anchor is the",
        "SpaceTimeDecodingDemo cell-3 value, reproduced below.",
        "",
        "| experiment | schedule | cycles | p_c per valid seed | failed fits | published | z | verdict |",
        "|---|---|---|---|---|---|---|---|",
    ]
    verdicts = []
    hk_rows = {}
    for (exp, cycles, sched, p_scale), runs in sorted(groups.items()):
        by_seed = {}
        for r in runs:
            by_seed[r["seed"]] = r  # latest rerun wins
        recs = [by_seed[s] for s in sorted(by_seed)]
        valid = [r for r in recs if fit_valid(r)]
        n_failed = len(recs) - len(valid)
        pcs = [r["p_c"] for r in valid]
        published = recs[0].get("published_p_c")
        if len(pcs) < 2:
            v, z = "FIT-UNSTABLE", None
        else:
            v, z = classify(pcs, published, exp)
        if p_scale != 1.0:
            # re-gridded sweep for a regenerated family whose crossing sits
            # off the published grid: the fitted p_c is a real measurement
            # of OUR members, but the published value was fit on a different
            # grid — report the number, never call it MATCH/MISMATCH.
            v = v if v in ("FIT-UNSTABLE", "NOISY") else "REGEN-DIFF(regridded)"
            z = None
        elif recs[0].get("published_suspect") and v in ("MATCH", "MISMATCH"):
            # the published value itself is a visibly broken reference fit
            # (see the experiment's suspect_cycles comment in parity.py):
            # tabulate our measurement with informational z, but don't let a
            # broken published number create a headline verdict either way
            v = "PUB-SUSPECT"
        if sched == "coloration" and p_scale == 1.0 and v != "PUB-SUSPECT":
            verdicts.append(v)
        if exp == "toric_circuit" and cycles in (25, 30) and p_scale == 1.0:
            hk_rows[(cycles, sched)] = (pcs, published)
        sched_str = sched if p_scale == 1.0 else f"{sched} (p x{p_scale:g})"
        pcs_str = ", ".join(f"{p:.4f}" for p in pcs) or "-"
        pub_str = f"{published:.4f}" if published is not None else "-"
        z_str = f"{z:.1f}" if z is not None else "-"
        lines.append(
            f"| {exp} | {sched_str} | {cycles} | {pcs_str} | {n_failed} | "
            f"{pub_str} | {z_str} | {v} |"
        )

    counts = {k: sum(v == k for v in verdicts)
              for k in ("MATCH", "NOISY", "REGEN-DIFF", "MISMATCH",
                        "FIT-UNSTABLE")}
    lines += [
        "",
        "**Reference-schedule rows: "
        + " / ".join(f"{n} {k}" for k, n in counts.items() if n)
        + f"** across {len(verdicts)} published values.",
        "",
        "NOISY rows are operating points where our own independent seeds",
        "disagree beyond 30% at the reference's sample counts — the (p_c, A)",
        "joint fit is ill-conditioned there (the p-grid sits far below the",
        "crossing point, so A and p_c trade off freely).  The reference's",
        "single-seed published number at those points carries the same",
        "variance.  REGEN-DIFF rows are the hgp_34 family, which is not",
        "apples-to-apples (regenerated members, see header comment in",
        "scripts/parity_report.py); their per-member effective distances",
        "are tabulated below as the defensible physics summary.",
        "",
    ]

    # ------------------------------------------------------------------
    # schedule A/B for the round-2 MISMATCH rows
    def _ab_line(cycles):
        kon = hk_rows.get((cycles, "coloration"))
        hk = hk_rows.get((cycles, "coloration_hk"))
        if not kon or not hk or not kon[0] or not hk[0]:
            return None
        mk, mh = float(np.mean(kon[0])), float(np.mean(hk[0]))
        pub = kon[1]
        return (f"| {cycles} | {mk:.5f} | {mh:.5f} | {pub:.5f} | "
                f"{(mk / pub - 1) * 100:+.0f}% | {(mh / pub - 1) * 100:+.0f}% |")

    ab = [_ab_line(c) for c in (25, 30)]
    if any(ab):
        lines += [
            "## Schedule A/B: Konig coloring vs the reference's exact",
            "Hopcroft-Karp coloration (toric_circuit)",
            "",
            "Round 2 left toric_circuit cycles 25/30 as MISMATCH with a",
            "schedule-sensitivity conjecture.  Round 3 implements the",
            "reference's exact padded-graph HK coloration",
            "(circuit_type='coloration_hk', circuits/scheduling.py) and",
            "reruns those cells:",
            "",
            "| cycles | p_c (Konig) | p_c (HK = reference) | published | "
            "Konig vs pub | HK vs pub |",
            "|---|---|---|---|---|---|",
            *[l for l in ab if l],
            "",
        ]

    # ------------------------------------------------------------------
    # hgp family: measured effective distances of the regenerated members
    d_eff = defaultdict(lambda: defaultdict(list))
    for (exp, cycles, sched, _p_scale), runs in groups.items():
        if exp not in _REGENERATED_FAMILY:
            continue
        for r in runs:
            for i, d in enumerate(r.get("d_eff") or []):
                d_eff[exp][i].append(d)
    if d_eff:
        lines += [
            "## Regenerated hgp family: measured effective distances",
            "",
            "Per-member d_eff from the notebook fit's first stage",
            "(log-log WER-vs-p slope = d_eff/2), averaged over all recorded",
            "sweeps — the instrument available for family-level physics",
            "when fitted p_c is not comparable:",
            "",
            "| experiment | member | mean d_eff | n sweeps |",
            "|---|---|---|---|",
        ]
        members = ["n225 ([[225,17]], exact seed)",
                   "n625 ([[625,25]], regenerated)",
                   "n1600 ([[1600,64]], regenerated)"]
        for exp in sorted(d_eff):
            for i in sorted(d_eff[exp]):
                ds = d_eff[exp][i]
                name = members[i] if i < len(members) else f"member {i}"
                lines.append(
                    f"| {exp} | {name} | {np.mean(ds):.2f} | {len(ds)} |")
        lines += [
            "",
            "Effective distance increases monotonically with member size in",
            "both noise models, as a working hgp family requires.",
            "",
        ]

    lines += [
        "## Direct-WER anchor (no fit)",
        "",
        "SpaceTimeDecodingDemo.ipynb cell 3 publishes a raw WER:",
        "1.930e-4 (toric d3, p_CX=1e-3, num_rep=3, 13 cycles, BP window +",
        "BPOSD final, 10k samples).  Executed unmodified through",
        "`compat.install()` (scripts/run_reference_notebook.py), this",
        "framework reproduces it within binomial error — see",
        "examples/executed/SpaceTimeDecodingDemo.executed.ipynb.",
    ]
    with open(args.out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
