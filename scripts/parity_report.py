"""Summarize PARITY_results.jsonl into PARITY_r2.md.

Groups runs by (experiment, cycles), reports the measured p_c per seed, the
seed spread, and the published reference value, and flags each row:
  MATCH    published value inside [min, max] of our seeds (or within 15% of
           the seed mean when all seeds agree tightly)
  NOISY    our own seeds disagree by >2x — the two-stage notebook fit is
           ill-conditioned at this operating point, for us and for the
           reference's single-seed published number alike
  MISMATCH seeds agree tightly with each other but not with the published
           value

Usage: python scripts/parity_report.py [--out PARITY_r2.md]
"""
import argparse
import json
import os
from collections import defaultdict

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def classify(pcs, published):
    lo, hi = min(pcs), max(pcs)
    mean = float(np.mean(pcs))
    if hi > 2 * lo:
        return "NOISY"
    if published is None:
        return "-"
    if lo * 0.85 <= published <= hi * 1.15:
        return "MATCH"
    if abs(published - mean) <= 0.15 * mean:
        return "MATCH"
    return "MISMATCH"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--results", default=os.path.join(REPO, "PARITY_results.jsonl"))
    ap.add_argument("--out", default=os.path.join(REPO, "PARITY_r2.md"))
    args = ap.parse_args()

    groups = defaultdict(list)
    for line in open(args.results):
        r = json.loads(line)
        groups[(r["experiment"], r["cycles"])].append(r)

    lines = [
        "# Physics parity vs the reference's published numbers (round 2)",
        "",
        "Each experiment replays a Threshold-checkpoint cell exactly — same",
        "codes, p-grid, decoder settings (incl. the notebook's q=0 quirk and",
        "even cycle counts), and the notebook's own two-stage ThresholdEst",
        "fit (per-code log-log distance fit, then joint EmpericalFit).",
        "Published values are single-seed notebook outputs; ours are run at",
        "multiple seeds so the fit variance is visible.  `scripts/parity.py`",
        "reproduces any row; raw per-cell WER grids are in",
        "PARITY_results.jsonl.",
        "",
        "| experiment | cycles | p_c per seed | published | verdict |",
        "|---|---|---|---|---|",
    ]
    verdicts = []
    for (exp, cycles), runs in sorted(groups.items()):
        # dedupe identical (seed) reruns, keep latest
        by_seed = {}
        for r in runs:
            by_seed[r["seed"]] = r
        pcs = [by_seed[s]["p_c"] for s in sorted(by_seed)]
        published = runs[0].get("published_p_c")
        v = classify(pcs, published)
        verdicts.append(v)
        pcs_str = ", ".join(f"{p:.4f}" for p in pcs)
        pub_str = f"{published:.4f}" if published is not None else "-"
        lines.append(f"| {exp} | {cycles} | {pcs_str} | {pub_str} | {v} |")

    n_match = sum(v == "MATCH" for v in verdicts)
    n_noisy = sum(v == "NOISY" for v in verdicts)
    n_mis = sum(v == "MISMATCH" for v in verdicts)
    lines += [
        "",
        f"**{n_match} MATCH / {n_noisy} NOISY / {n_mis} MISMATCH** "
        f"across {len(verdicts)} published values.",
        "",
        "NOISY rows are operating points where our own independent seeds",
        "disagree by >2x at the reference's sample counts — the (p_c, A)",
        "joint fit is ill-conditioned there (the p-grid sits far below the",
        "crossing point, so A and p_c trade off freely).  The reference's",
        "single-seed published number at those points carries the same",
        "variance.",
        "",
        "## Direct-WER anchor (no fit)",
        "",
        "SpaceTimeDecodingDemo.ipynb cell 3 publishes a raw WER:",
        "1.930e-4 (toric d3, p_CX=1e-3, num_rep=3, 13 cycles, BP window +",
        "BPOSD final, 10k samples).  Executed unmodified through",
        "`compat.install()` (scripts/run_reference_notebook.py), this",
        "framework reproduces it within binomial error — see",
        "examples/executed/SpaceTimeDecodingDemo.executed.ipynb.",
    ]
    with open(args.out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {args.out}")
    print("\n".join(lines[-20:]))


if __name__ == "__main__":
    main()
