"""Live terminal dashboard for sweep runs: the (code x p) grid with per-cell
state, CI width and shot counts, rendered from the statistical-observability
layer's outputs alone — no live process required.

    python scripts/sweep_dashboard.py ledger/                 # last run's grid
    python scripts/sweep_dashboard.py ledger/sweeps.jsonl
    python scripts/sweep_dashboard.py run.jsonl --follow      # tail a live sink
    python scripts/sweep_dashboard.py ledger/ --drift         # cross-run compare
    python scripts/sweep_dashboard.py ledger/ --drift --gate 3

Inputs (auto-detected per line, freely mixable):
  * run-ledger records (utils.diagnostics.RunLedger — one JSON object per
    sweep run with per-cell final counts + Wilson CIs, fit reports,
    anomalies), written under a ``ledger/`` dir by
    ``CodeFamily.EvalWER(..., ledger=...)`` / ``QLDPC_LEDGER_DIR``;
  * raw telemetry JSONL event streams (utils.telemetry JsonlSink):
    ``cell_done`` events fill the grid, ``cell_progress`` events (the fused
    drivers' live per-cell intervals) mark still-running cells, ``anomaly``
    events flag cells, ``fit_report`` events list below the grid, and the
    decode service's ``serve_*`` events (schema v2) fold into a per-session
    serve block instead of being dropped.

Views (``--view``): ``wer`` (default; WER with relative CI width), ``ci``
(interval bounds on the failure rate), ``shots``, ``state``, ``ess``
(effective sample size / shots — the importance-sampled cells' health
column; direct cells show their plain shot count).  Weighted cells (the
rare/ subsystem, event schema v3) are marked ``*`` in every view so a
mixed direct/weighted grid reads at a glance.

``--drift`` compares the LAST ledger run against the most recent prior run
with the SAME config fingerprint (bench_compare's regression-ledger idea,
applied to physics numbers): per-cell failure-rate deltas in combined-sigma
units.  ``--gate Z`` exits 1 when any |z| exceeds Z — wire it into CI to
catch silently shifted physics.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# Input loading
# ---------------------------------------------------------------------------
def resolve_path(path: str) -> str:
    """A directory means its ledger file (utils.diagnostics.RunLedger)."""
    if os.path.isdir(path):
        return os.path.join(path, "sweeps.jsonl")
    return path


def load_lines(path: str) -> list[dict]:
    """Parse one JSONL file (ledger records and/or telemetry events) —
    the library's crash-tolerant loader handles the torn-line and
    dir -> sweeps.jsonl conventions in ONE place."""
    from qldpc_fault_tolerance_tpu.utils.diagnostics import load_ledger

    return load_ledger(path)


# ---------------------------------------------------------------------------
# Grid model
# ---------------------------------------------------------------------------
def _cell_update(grid: dict, key: dict, fields: dict, state: str) -> None:
    row = (str(key.get("code", "?")), str(key.get("type", "?")),
           str(key.get("noise", "?")))
    p = float(key.get("p", 0.0))
    cell = grid["rows"].setdefault(row, {}).setdefault(p, {})
    # events are chronological within a stream, so the LAST update wins —
    # a later run's progress correctly reopens a cell an earlier run (or
    # ledger record) finished
    cell.update({k: v for k, v in fields.items() if v is not None})
    cell["state"] = state


def build_grid(records: list[dict], grid: dict | None = None) -> dict:
    """Fold ledger records / telemetry events into the grid model:
    ``{"rows": {(code, type, noise): {p: cell}}, "anomalies": [...],
    "fits": [...], "runs": [...]}``.  Pass the previous ``grid`` to fold
    incrementally (the --follow loop feeds only fresh records instead of
    re-parsing the whole history every poll)."""
    if grid is None:
        grid = {"rows": {}, "anomalies": [], "fits": [], "runs": []}
    # decode-service events (utils.telemetry schema v2) fold into a serve
    # summary instead of being dropped: per-session request/shot/batch
    # totals, last occupancy, tenant set, drain marker
    serve = grid.setdefault(
        "serve", {"sessions": {}, "drains": 0, "errors": 0})
    for rec in records:
        kind = rec.get("kind")
        if kind is None and "cells" in rec and "run_id" in rec:
            # run-ledger record
            grid["runs"].append({"run_id": rec.get("run_id"),
                                 "fingerprint": rec.get("fingerprint"),
                                 "ts": rec.get("ts")})
            for c in rec.get("cells", []):
                _cell_update(grid, c.get("cell", {}),
                             {k: c.get(k) for k in
                              ("wer", "failures", "shots", "rate", "ci_low",
                               "ci_high", "rel_ci_width", "rse",
                               "substrate", "ess", "tilt")},
                             "done")
            grid["anomalies"].extend(rec.get("anomalies", []))
            grid["fits"].extend(rec.get("fits", []))
        elif kind == "cell_done":
            _cell_update(grid, rec,
                         {k: rec.get(k) for k in
                          ("wer", "failures", "shots", "rate", "ci_low",
                           "ci_high", "rel_ci_width", "rse", "ess",
                           "tilt", "log_weight_sum")},
                         "done")
        elif kind == "cell_progress":
            n_cells = len(rec.get("cells", []))
            for c, f, n, lo, hi, rse, ess in zip(
                    rec.get("cells", []), rec.get("failures", []),
                    rec.get("shots", []), rec.get("ci_low", []),
                    rec.get("ci_high", []),
                    rec.get("rse") or [None] * n_cells,
                    rec.get("ess") or [None] * n_cells):
                key = c if isinstance(c, dict) else {"p": c}
                key.setdefault("code", f"({rec.get('engine', '?')})")
                rate = (f / n) if n else 0.0
                _cell_update(grid, key,
                             {"failures": f, "shots": n, "rate": rate,
                              "ci_low": lo, "ci_high": hi, "rse": rse,
                              "ess": ess,
                              "rel_ci_width": ((hi - lo) / rate
                                               if rate > 0 else None)},
                             "running")
        elif kind == "anomaly":
            grid["anomalies"].append(rec)
        elif kind == "fit_report":
            grid["fits"].append(rec)
        elif kind in ("serve_request", "serve_batch", "serve_session"):
            name = str(rec.get("session", "?"))
            s = serve["sessions"].setdefault(
                name, {"requests": 0, "shots": 0, "batches": 0,
                       "compiles": 0, "occupancy": None, "tenants": set()})
            if kind == "serve_request":
                s["requests"] += 1
                s["shots"] += int(rec.get("shots", 0))
                s["tenants"].add(str(rec.get("tenant", "?")))
                if rec.get("ok") is False:
                    serve["errors"] += 1
            elif kind == "serve_batch":
                s["batches"] += 1
                if rec.get("occupancy") is not None:
                    s["occupancy"] = rec["occupancy"]
                if rec.get("ok") is False:
                    serve["errors"] += int(rec.get("requests", 1))
            else:  # serve_session
                if rec.get("event") == "compile":
                    s["compiles"] += 1
        elif kind == "serve_drain":
            serve["drains"] += 1
        elif kind == "snapshot":
            # latest registry snapshot wins: gauge last-set stamps
            # (ISSUE 17) let the render mark frozen values as stale
            grid["snapshot"] = {"ts": rec.get("ts"),
                                "metrics": rec.get("metrics", {})}
    # mark anomalous cells
    for a in grid["anomalies"]:
        cell_key = a.get("cell")
        if isinstance(cell_key, dict):
            row = (str(cell_key.get("code", "?")),
                   str(cell_key.get("type", "?")),
                   str(cell_key.get("noise", "?")))
            p = float(cell_key.get("p", 0.0))
            c = grid["rows"].get(row, {}).get(p)
            if c is not None:
                c["anomaly"] = True
    return grid


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------
def _fmt(x, width: int) -> str:
    return f"{x:>{width}}"


def _cell_text(cell: dict, view: str) -> str:
    if cell is None:
        return "-"
    mark = "!" if cell.get("anomaly") else ("~" if cell.get("state") ==
                                            "running" else "")
    # importance-sampled cells (rare/ subsystem, event schema v3) carry an
    # effective sample size; the * mark keeps a mixed direct/weighted grid
    # readable at a glance
    if cell.get("ess") is not None:
        mark += "*"
    if view == "state":
        return mark + (cell.get("state") or "?")
    if view == "ess":
        ess = cell.get("ess")
        n = cell.get("shots")
        if ess is None:
            return mark + ("-" if n is None else f"{n}")
        return f"{mark}{ess:.3g}/{n}" if n else f"{mark}{ess:.3g}"
    if view == "shots":
        n = cell.get("shots")
        f = cell.get("failures")
        if n is None:
            return mark + "?"
        return f"{mark}{f}/{n}" if f is not None else f"{mark}{n}"
    if view == "ci":
        lo, hi = cell.get("ci_low"), cell.get("ci_high")
        if lo is None or hi is None:
            return mark + "?"
        return f"{mark}[{lo:.1e},{hi:.1e}]"
    # default: wer with relative CI width
    wer = cell.get("wer", cell.get("rate"))
    if wer is None:
        return mark + "?"
    rw = cell.get("rel_ci_width")
    pct = f"±{50 * rw:.0f}%" if rw is not None else ""
    return f"{mark}{wer:.2e}{pct}"


def render_grid(grid: dict, view: str = "wer", title: str = "") -> str:
    """The terminal grid: one row block per (code, type, noise), one column
    per p."""
    lines = [f"== qldpc sweep dashboard{': ' + title if title else ''} =="]
    if grid["runs"]:
        last = grid["runs"][-1]
        lines.append(f"runs: {len(grid['runs'])}   latest "
                     f"{last.get('run_id')} (config {last.get('fingerprint')})")
    serve = grid.get("serve") or {}
    if not grid["rows"]:
        if serve.get("sessions"):
            lines.extend(_serve_lines(serve))
            lines.extend(_stale_gauge_lines(grid))
            return "\n".join(lines)
        lines.append("(no cells yet)")
        return "\n".join(lines)
    all_p = sorted({p for cells in grid["rows"].values() for p in cells})
    width = max(14, max((len(_cell_text(c, view))
                         for cells in grid["rows"].values()
                         for c in cells.values()), default=14) + 2)
    label_w = max(len(f"{code} {lt} ({noise})")
                  for code, lt, noise in grid["rows"]) + 2
    header = " " * label_w + "".join(_fmt(f"p={p:g}", width) for p in all_p)
    lines.append("")
    lines.append(f"-- grid ({view}; ~ running, ! anomaly, "
                 "* importance-sampled) --")
    lines.append(header)
    for (code, lt, noise), cells in sorted(grid["rows"].items()):
        label = f"{code} {lt} ({noise})"
        row = f"{label:<{label_w}}" + "".join(
            _fmt(_cell_text(cells.get(p), view), width) for p in all_p)
        lines.append(row)
    done = sum(1 for cells in grid["rows"].values()
               for c in cells.values() if c.get("state") == "done")
    total = sum(len(cells) for cells in grid["rows"].values())
    lines.append(f"cells: {done}/{total} done")
    if grid["fits"]:
        lines.append("-- fits --")
        for f in grid["fits"]:
            bits = [f.get("fit", "?"),
                    "ok" if f.get("converged") else "FAILED"]
            if f.get("p_c") is not None:
                bits.append(f"p_c={f['p_c']:.4g}")
            if f.get("pc_ci"):
                bits.append(f"ci=[{f['pc_ci'][0]:.4g},{f['pc_ci'][1]:.4g}]")
            if f.get("d_eff") is not None:
                bits.append(f"d_eff={f['d_eff']:.3g}")
            if f.get("d_ci"):
                bits.append(f"ci=[{f['d_ci'][0]:.3g},{f['d_ci'][1]:.3g}]")
            if f.get("r2") is not None:
                bits.append(f"r2={f['r2']:.4f}")
            lines.append("  " + "  ".join(bits))
    if grid["anomalies"]:
        lines.append(f"-- anomalies ({len(grid['anomalies'])}) --")
        for a in grid["anomalies"]:
            kind = a.get("anomaly", "?")
            cell = a.get("cell") or {}
            where = (f"{cell.get('code', '')} p={cell.get('p', '')}"
                     if cell else "")
            detail = {k: v for k, v in a.items()
                      if k not in ("anomaly", "cell", "ts", "kind")}
            lines.append(f"  ! {kind} {where} {json.dumps(detail, default=str)}"
                         .rstrip())
    if serve.get("sessions"):
        lines.extend(_serve_lines(serve))
    lines.extend(_stale_gauge_lines(grid))
    return "\n".join(lines)


def _stale_gauge_lines(grid: dict) -> list[str]:
    """Mark gauges whose last-set stamp lags the latest snapshot (ISSUE
    17): a frozen queue depth must read as stale, not as current state."""
    snap = grid.get("snapshot")
    if not snap:
        return []
    from scripts.telemetry_report import stale_gauges

    stale = stale_gauges(snap.get("metrics", {}), snap.get("ts"))
    if not stale:
        return []
    lines = ["-- stale gauges (frozen values) --"]
    for name, age in sorted(stale.items()):
        lines.append(f"  {name:<30}last set {age}s before snapshot")
    return lines


def _serve_lines(serve: dict) -> list[str]:
    """The decode-service block: per-session request/shot/batch totals."""
    lines = ["-- serve (decode service) --"]
    for name, s in sorted(serve["sessions"].items()):
        occ = (f"  occ {s['occupancy']:.2f}"
               if s.get("occupancy") is not None else "")
        lines.append(
            f"  {name:<24}{s['requests']:>7} req  {s['shots']:>8} shots  "
            f"{s['batches']:>6} batches  {len(s['tenants'])} tenant(s)"
            f"{occ}"
            + (f"  {s['compiles']} compiles" if s.get("compiles") else ""))
    tail = []
    if serve.get("errors"):
        tail.append(f"{serve['errors']} failed request(s)")
    if serve.get("drains"):
        tail.append(f"{serve['drains']} drain(s)")
    if tail:
        lines.append("  " + ", ".join(tail))
    return lines


# ---------------------------------------------------------------------------
# Cross-run drift
# ---------------------------------------------------------------------------
def _cell_map(rec: dict) -> dict:
    out = {}
    for c in rec.get("cells", []):
        k = c.get("cell", {})
        out[(str(k.get("code")), str(k.get("type")), str(k.get("noise")),
             round(float(k.get("p", 0.0)), 12))] = c
    return out


def drift_report(records: list[dict]) -> dict | None:
    """Compare the LAST ledger run against the most recent PRIOR run with
    the same config fingerprint: per-cell failure-rate deltas in
    combined-sigma units (binomial se from each run's own counts).
    Runs marked ``complete: false`` (the sweep raised mid-grid) are
    excluded — gating against a truncated run would pass vacuously.
    Returns None when no comparable pair exists."""
    runs = [r for r in records if "cells" in r and "run_id" in r
            and r.get("complete", True)]
    if len(runs) < 2:
        return None
    cur = runs[-1]
    prior = next((r for r in reversed(runs[:-1])
                  if r.get("fingerprint") == cur.get("fingerprint")), None)
    if prior is None:
        return None
    rows = []
    cur_cells, prior_cells = _cell_map(cur), _cell_map(prior)
    for key in sorted(set(cur_cells) & set(prior_cells)):
        a, b = prior_cells[key], cur_cells[key]
        if not all(x.get("shots") for x in (a, b)):
            continue
        ra = a["failures"] / a["shots"]
        rb = b["failures"] / b["shots"]
        se2 = (ra * (1 - ra) / a["shots"]) + (rb * (1 - rb) / b["shots"])
        z = (rb - ra) / se2**0.5 if se2 > 0 else (
            0.0 if rb == ra else float("inf"))
        rows.append({"cell": key, "rate_prior": ra, "rate_now": rb,
                     "z": z})
    return {
        "prior_run": prior.get("run_id"), "now_run": cur.get("run_id"),
        "fingerprint": cur.get("fingerprint"),
        "cells": rows,
        "max_abs_z": max((abs(r["z"]) for r in rows), default=0.0),
        "env_changes": _env_changes(prior, cur),
    }


# provenance keys worth flagging between runs (utils.telemetry
# process_info; pid churns per process and means nothing for drift)
_ENV_DRIFT_KEYS = ("git_sha", "jax", "jaxlib", "backend", "hostname",
                   "python")


def _env_changes(prior: dict, cur: dict) -> list[dict]:
    """Provenance deltas between two ledger records' ``env`` blocks
    (ISSUE 11): a WER shift that coincides with a jax/backend/host change
    is an environment story, not a physics regression.  Records from
    before the env block simply compare as no-change."""
    a, b = prior.get("env"), cur.get("env")
    if not a or not b:
        # a record from before the env block carries no provenance to
        # compare against — flagging every key as "changed" would blame
        # the environment for drift on the first post-upgrade report
        return []
    return [{"key": k, "prior": a.get(k), "now": b.get(k)}
            for k in _ENV_DRIFT_KEYS
            if a.get(k) != b.get(k) and (a.get(k) or b.get(k))]


def render_drift(report: dict) -> str:
    L = [f"== sweep drift: {report['prior_run']} -> {report['now_run']} "
         f"(config {report['fingerprint']}) =="]
    L.append(f"  {'cell':<44}{'prior':>12}{'now':>12}{'z':>8}")
    for r in report["cells"]:
        code, lt, noise, p = r["cell"]
        name = f"{code} {lt} ({noise}) p={p:g}"
        L.append(f"  {name:<44}{r['rate_prior']:>12.3e}"
                 f"{r['rate_now']:>12.3e}{r['z']:>8.2f}")
    L.append(f"max |z| = {report['max_abs_z']:.2f}")
    changes = report.get("env_changes") or []
    if changes:
        L.append("environment changed between runs (drift may not be "
                 "physics):")
        for c in changes:
            L.append(f"  {c['key']}: {c['prior']} -> {c['now']}")
    else:
        L.append("environment unchanged between runs")
    return "\n".join(L)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="run-ledger dir/.jsonl or telemetry JSONL "
                                 "stream")
    ap.add_argument("--view", choices=("wer", "ci", "shots", "state", "ess"),
                    default="wer")
    ap.add_argument("--follow", action="store_true",
                    help="tail the file and re-render on new lines")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="--follow poll interval in seconds")
    ap.add_argument("--drift", action="store_true",
                    help="compare the last ledger run against the prior "
                         "run with the same config fingerprint")
    ap.add_argument("--gate", type=float, default=None,
                    help="with --drift: exit 1 when any |z| exceeds this")
    ap.add_argument("--json", action="store_true",
                    help="emit the grid/drift model as json")
    args = ap.parse_args(argv)

    path = resolve_path(args.path)
    if args.drift:
        report = drift_report(load_lines(path))
        if report is None:
            print("no comparable ledger run pair (need two complete runs "
                  "with the same config fingerprint)", file=sys.stderr)
            # under --gate this is the CI bootstrap case (first run after
            # a fresh ledger or a config change): nothing to gate, so pass
            # — a red exit here would be indistinguishable from real drift
            return 0 if args.gate is not None else 1
        if args.json:
            print(json.dumps(report, default=str))
        else:
            print(render_drift(report))
        if args.gate is not None and report["max_abs_z"] > args.gate:
            print(f"DRIFT GATE FAILED: max |z| {report['max_abs_z']:.2f} "
                  f"> {args.gate}", file=sys.stderr)
            return 1
        return 0

    if args.follow:
        from scripts.telemetry_report import FollowReader

        reader = FollowReader(path)
        # incremental fold: only FRESH records are parsed each poll, so a
        # multi-hour stream doesn't degrade the refresh or grow memory
        grid = build_grid([])
        seen_any = False
        try:
            while True:
                fresh = reader.poll()
                if fresh or not seen_any:
                    seen_any = seen_any or bool(fresh)
                    grid = build_grid(fresh, grid)
                    if sys.stdout.isatty():
                        sys.stdout.write("\x1b[2J\x1b[H")
                    print(render_grid(grid, args.view,
                                      title=os.path.basename(path)
                                      + " (following)"))
                    sys.stdout.flush()
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0

    records = load_lines(path)
    if not records:
        print(f"no records in {path}", file=sys.stderr)
        return 1
    grid = build_grid(records)
    if args.json:
        serve = grid.get("serve") or {}
        out = {"rows": {f"{c}|{t}|{n}": cells
                        for (c, t, n), cells in grid["rows"].items()},
               "anomalies": grid["anomalies"], "fits": grid["fits"],
               "runs": grid["runs"],
               "serve": {**serve,
                         "sessions": {
                             name: {**s, "tenants": sorted(s["tenants"])}
                             for name, s in serve.get("sessions",
                                                      {}).items()}}}
        print(json.dumps(out, default=str))
        return 0
    print(render_grid(grid, args.view, title=os.path.basename(args.path)))
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:  # e.g. `... | head` — not an error
        raise SystemExit(0)
