"""Independent Pauli-frame simulator + sampler/decoder A/B harness.

Root-cause instrument for the circuit-level p_c offset (VERDICT r3 #2): the
production `circuits/sampler.py` FrameSampler is a fused XLA program with
scatter-free index tricks; this module is a deliberately naive, from-scratch
numpy frame simulator written directly from stim's documented Pauli-frame
semantics (stim.TableauSimulator/FrameSimulator reference docs) — including
stim's reset randomization (after R the frame is randomized to {I, Z}; after
RX to {I, X}) that the production sampler replaces with frame clearing.  If
the two samplers disagree on detector/observable statistics, the production
sampler is wrong; if they agree, the sampler is exonerated and the offset
must come from decoding or fit protocol.

Three instruments:

  * ``single_fault_patterns``: enumerate every possible single-fault outcome
    of every noise site and propagate it noiselessly -> the exact linear
    fault->detector matrix.  Because frame propagation is linear over GF(2),
    agreement on ALL single-fault patterns plus iid fault drawing implies
    full distributional agreement — a complete check, stronger than any chi^2.
  * ``compare_moments``: empirical detector marginals AND pairwise moments,
    production sampler vs this simulator, z-scored.
  * decode A/B (``--mode decode``): feed both samplers' detector batches
    through the SAME production decode chain at one operating point; any WER
    gap isolates sampling (vs decoding) as the cause.

Usage:
  JAX_PLATFORMS=cpu python scripts/ab_frame_sim.py --mode faults
  JAX_PLATFORMS=cpu python scripts/ab_frame_sim.py --mode moments --shots 200000
  JAX_PLATFORMS=cpu python scripts/ab_frame_sim.py --mode decode --shots 20000
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

from qldpc_fault_tolerance_tpu.circuits.ir import (  # noqa: E402
    Circuit,
    RecTarget,
)


# ---------------------------------------------------------------------------
# naive frame simulator (stim semantics, written independently of sampler.py)
class NaiveFrameSim:
    """Batched but structurally naive: one python step per instruction, one
    numpy op per target pair — no fusing, no index maps, no scan."""

    def __init__(self, circuit: Circuit):
        self.instrs = list(circuit.flattened())
        self.nq = circuit.num_qubits
        self.num_meas = circuit.num_measurements
        self.num_det = circuit.num_detectors
        self.num_obs = circuit.num_observables

    def run(self, shots: int, rng: np.random.Generator,
            randomize_resets: bool = True,
            forced_fault: tuple | None = None):
        """Returns (dets, obs) uint8 arrays.

        ``forced_fault=(site_index, outcome)``: disable ALL random noise and
        deterministically apply outcome at the site_index-th noise
        instruction (see ``noise_sites``); resets are not randomized in this
        mode so the propagation is exactly the single-fault pattern.
        """
        B, nq = shots, self.nq
        x = np.zeros((B, nq), np.uint8)
        z = np.zeros((B, nq), np.uint8)
        rec = np.zeros((B, self.num_meas), np.uint8)
        dets = np.zeros((B, self.num_det), np.uint8)
        obs = np.zeros((B, self.num_obs), np.uint8)
        mcount = 0
        dcount = 0
        site = -1
        randomize = randomize_resets and forced_fault is None
        for ins in self.instrs:
            name = ins.name
            if name in ("X_ERROR", "Y_ERROR", "Z_ERROR", "DEPOLARIZE1",
                        "DEPOLARIZE2"):
                site += 1
                if forced_fault is not None:
                    if site == forced_fault[0]:
                        self._apply_forced(ins, forced_fault[1], x, z)
                    continue
                p = float(ins.args[0]) if ins.args else 0.0
                if p == 0.0:
                    continue
                self._apply_random(ins, p, x, z, rng)
            elif name == "CX":
                ts = ins.targets
                for i in range(0, len(ts), 2):
                    c, t = ts[i], ts[i + 1]
                    x[:, t] ^= x[:, c]
                    z[:, c] ^= z[:, t]
            elif name == "CZ":
                ts = ins.targets
                for i in range(0, len(ts), 2):
                    a, b = ts[i], ts[i + 1]
                    z[:, b] ^= x[:, a]
                    z[:, a] ^= x[:, b]
            elif name == "H":
                for q in ins.targets:
                    x[:, q], z[:, q] = z[:, q].copy(), x[:, q].copy()
            elif name == "R":
                for q in ins.targets:
                    x[:, q] = 0
                    # |0> is Z-stabilized: frame Z is unobservable; stim
                    # randomizes it to surface non-deterministic detectors
                    z[:, q] = (rng.integers(0, 2, B, dtype=np.uint8)
                               if randomize else 0)
            elif name == "RX":
                for q in ins.targets:
                    z[:, q] = 0
                    x[:, q] = (rng.integers(0, 2, B, dtype=np.uint8)
                               if randomize else 0)
            elif name in ("M", "MR", "MX"):
                for q in ins.targets:
                    if name == "MX":
                        rec[:, mcount] = z[:, q]
                        # post-measurement state is X-stabilized
                        if randomize:
                            x[:, q] ^= rng.integers(0, 2, B, dtype=np.uint8)
                    else:
                        rec[:, mcount] = x[:, q]
                        if name == "MR":
                            x[:, q] = 0
                            z[:, q] = (rng.integers(0, 2, B, dtype=np.uint8)
                                       if randomize else 0)
                        elif randomize:
                            z[:, q] ^= rng.integers(0, 2, B, dtype=np.uint8)
                    mcount += 1
            elif name == "DETECTOR":
                for t in ins.targets:
                    assert isinstance(t, RecTarget)
                    dets[:, dcount] ^= rec[:, mcount + t.offset]
                dcount += 1
            elif name == "OBSERVABLE_INCLUDE":
                k = int(ins.args[0]) if ins.args else 0
                for t in ins.targets:
                    obs[:, k] ^= rec[:, mcount + t.offset]
            elif name in ("TICK", "SHIFT_COORDS"):
                pass
            else:
                raise AssertionError(f"unhandled instruction {name}")
        assert mcount == self.num_meas and dcount == self.num_det
        return dets, obs

    # -- noise application ---------------------------------------------------
    @staticmethod
    def _apply_random(ins, p, x, z, rng):
        B = x.shape[0]
        name = ins.name
        if name == "DEPOLARIZE2":
            ts = ins.targets
            for i in range(0, len(ts), 2):
                a, b = ts[i], ts[i + 1]
                hit = rng.random(B) < p
                pauli = rng.integers(1, 16, B)  # uniform over 15 non-II
                p1, p2 = pauli >> 2, pauli & 3
                x[:, a] ^= (hit & ((p1 == 1) | (p1 == 2))).astype(np.uint8)
                z[:, a] ^= (hit & ((p1 == 2) | (p1 == 3))).astype(np.uint8)
                x[:, b] ^= (hit & ((p2 == 1) | (p2 == 2))).astype(np.uint8)
                z[:, b] ^= (hit & ((p2 == 2) | (p2 == 3))).astype(np.uint8)
        elif name == "DEPOLARIZE1":
            for q in ins.targets:
                hit = rng.random(B) < p
                pauli = rng.integers(1, 4, B)  # uniform over X, Y, Z
                x[:, q] ^= (hit & ((pauli == 1) | (pauli == 2))).astype(np.uint8)
                z[:, q] ^= (hit & ((pauli == 2) | (pauli == 3))).astype(np.uint8)
        else:
            fx = name in ("X_ERROR", "Y_ERROR")
            fz = name in ("Z_ERROR", "Y_ERROR")
            for q in ins.targets:
                hit = (rng.random(B) < p).astype(np.uint8)
                if fx:
                    x[:, q] ^= hit
                if fz:
                    z[:, q] ^= hit

    @staticmethod
    def _apply_forced(ins, outcome, x, z):
        """outcome: (target_group_index, pauli_code); pauli codes follow
        stim's DEPOLARIZE ordering (1..15 two-qubit, 1..3 single-qubit)."""
        gi, code = outcome
        name = ins.name
        if name == "DEPOLARIZE2":
            a, b = ins.targets[2 * gi], ins.targets[2 * gi + 1]
            p1, p2 = code >> 2, code & 3
            x[:, a] ^= np.uint8((p1 == 1) | (p1 == 2))
            z[:, a] ^= np.uint8((p1 == 2) | (p1 == 3))
            x[:, b] ^= np.uint8((p2 == 1) | (p2 == 2))
            z[:, b] ^= np.uint8((p2 == 2) | (p2 == 3))
        elif name == "DEPOLARIZE1":
            q = ins.targets[gi]
            x[:, q] ^= np.uint8((code == 1) | (code == 2))
            z[:, q] ^= np.uint8((code == 2) | (code == 3))
        else:
            q = ins.targets[gi]
            if name in ("X_ERROR", "Y_ERROR"):
                x[:, q] ^= 1
            if name in ("Z_ERROR", "Y_ERROR"):
                z[:, q] ^= 1

    # -- fault enumeration ---------------------------------------------------
    def noise_sites(self):
        """Yield (site_index, instruction) for every noise instruction in
        flattened order (the indexing ``forced_fault`` uses)."""
        site = -1
        for ins in self.instrs:
            if ins.name in ("X_ERROR", "Y_ERROR", "Z_ERROR", "DEPOLARIZE1",
                            "DEPOLARIZE2"):
                site += 1
                yield site, ins

    def single_fault_patterns(self):
        """Enumerate all (site, group, pauli) single faults -> dict mapping
        fault key to (det_pattern, obs_pattern) uint8 vectors.  Zero-prob
        sites are skipped (they can never fire)."""
        out = {}
        for site, ins in self.noise_sites():
            p = float(ins.args[0]) if ins.args else 0.0
            if p == 0.0:
                continue
            if ins.name == "DEPOLARIZE2":
                groups = len(ins.targets) // 2
                codes = range(1, 16)
            elif ins.name == "DEPOLARIZE1":
                groups = len(ins.targets)
                codes = range(1, 4)
            else:
                groups = len(ins.targets)
                codes = (1,)
            for gi in range(groups):
                for code in codes:
                    dets, obs = self.run(
                        1, np.random.default_rng(0),
                        forced_fault=(site, (gi, code)))
                    out[(site, gi, code)] = (dets[0].copy(), obs[0].copy())
        return out


# ---------------------------------------------------------------------------
def build_toric_circuit(d: int, cycles: int, p: float,
                        circuit_type: str = "coloration"):
    from qldpc_fault_tolerance_tpu.codes import hgp, ring_code
    from qldpc_fault_tolerance_tpu.sim.circuit import build_memory_circuit
    from qldpc_fault_tolerance_tpu.circuits import (
        ColorationCircuit, RandomCircuit)

    code = hgp(ring_code(d), ring_code(d), name=f"toric_d{d}")
    ep = {"p_i": 0, "p_state_p": 0, "p_m": 0, "p_CX": p, "p_idling_gate": 0}
    sched = (RandomCircuit if circuit_type == "random" else ColorationCircuit)
    circ = build_memory_circuit(code, cycles, ep, sched(code.hx),
                                sched(code.hz))
    return code, circ


def mode_faults(args):
    """Sampler-vs-naive at the single-fault level: force each possible fault
    through BOTH implementations.  The production sampler has no injection
    hook, so the comparison runs through its linearity: with exactly one
    noise site's probability set to 1 and a pinned uniform draw we can't
    steer XLA's component choice — instead we exploit that at p extremely
    small the production batch containing exactly one firing site realizes
    one single-fault pattern; matching every naive pattern against the
    production-observed pattern SET checks the reachable pattern space.
    Primary instrument: the naive enumeration itself, cross-checked between
    randomize_resets on/off (stim-semantics invisibility) and against the
    production sampler's empirical moments in --mode moments."""
    code, circ = build_toric_circuit(args.d, args.cycles, args.p)
    sim = NaiveFrameSim(circ)
    pats = sim.single_fault_patterns()
    n_sites = sum(1 for _ in sim.noise_sites())
    # stim invariant: single faults never flip an observable without flipping
    # a detector somewhere (else the code distance would be 1)
    bad = [k for k, (d_, o_) in pats.items() if o_.any() and not d_.any()]
    print(f"circuit: toric d{args.d}, {args.cycles} cycles, p={args.p}")
    print(f"noise sites: {n_sites}; enumerated fault outcomes: {len(pats)}")
    print(f"undetectable logical single faults: {len(bad)} (must be 0)")
    # reset-randomization invisibility: detector/observable single-fault
    # patterns must not depend on reset frame randomization (checked by
    # construction: forced mode disables randomization); empirical check of
    # the noiseless circuit instead:
    rng = np.random.default_rng(7)
    _, circ0 = build_toric_circuit(args.d, args.cycles, 0.0)
    dets0, obs0 = NaiveFrameSim(circ0).run(512, rng, randomize_resets=True)
    print(f"noiseless naive sim with stim reset randomization: "
          f"det flips {int(dets0.sum())}, obs flips {int(obs0.sum())} "
          f"(must be 0/0 — detectors deterministic)")
    assert not bad and not dets0.any() and not obs0.any()
    print("FAULTS-OK")


def _pair_moments(dets: np.ndarray, max_dets: int = 400):
    """Marginals and pairwise AND-moments (subsampled columns if wide)."""
    B, D = dets.shape
    cols = np.arange(D) if D <= max_dets else np.linspace(
        0, D - 1, max_dets).astype(int)
    sub = dets[:, cols].astype(np.float32)
    marg = sub.mean(0)
    pair = (sub.T @ sub) / B
    return cols, marg, pair


def mode_moments(args):
    import jax

    from qldpc_fault_tolerance_tpu.circuits import FrameSampler

    code, circ = build_toric_circuit(args.d, args.cycles, args.p)
    sim = NaiveFrameSim(circ)
    shots = args.shots
    rng = np.random.default_rng(3)
    dn_parts, on_parts = [], []
    chunk = 20000
    for i in range(0, shots, chunk):
        d_, o_ = sim.run(min(chunk, shots - i), rng)
        dn_parts.append(d_)
        on_parts.append(o_)
    dets_n = np.concatenate(dn_parts)
    obs_n = np.concatenate(on_parts)

    sampler = FrameSampler(circ)
    dets_p, obs_p = [], []
    for i in range(0, shots, chunk):
        d_, o_ = sampler.sample(jax.random.PRNGKey(1000 + i),
                                min(chunk, shots - i))
        dets_p.append(np.asarray(d_))
        obs_p.append(np.asarray(o_))
    dets_p = np.concatenate(dets_p)
    obs_p = np.concatenate(obs_p)

    cols, marg_n, pair_n = _pair_moments(dets_n)
    _, marg_p, pair_p = _pair_moments(dets_p)
    B = shots
    eps = 1e-12
    z_marg = np.abs(marg_p - marg_n) / np.sqrt(
        (marg_n * (1 - marg_n) + marg_p * (1 - marg_p)) / B + eps)
    z_pair = np.abs(pair_p - pair_n) / np.sqrt(
        (pair_n * (1 - pair_n) + pair_p * (1 - pair_p)) / B + eps)
    iu = np.triu_indices_from(pair_n, k=1)
    print(f"shots={B} dets={dets_n.shape[1]} (compared cols: {len(cols)})")
    print(f"det marginal mean: naive {marg_n.mean():.6f} "
          f"prod {marg_p.mean():.6f}")
    print(f"marginal |z|: max {z_marg.max():.2f} "
          f"frac>3 {float((z_marg > 3).mean()):.4f} (expect ~0.003)")
    print(f"pairwise |z|: max {z_pair[iu].max():.2f} "
          f"frac>3 {float((z_pair[iu] > 3).mean()):.4f} (expect ~0.003)")
    print(f"obs rate: naive {obs_n.mean():.6f} prod {obs_p.mean():.6f}")
    shot_w_n = dets_n.sum(1).mean()
    shot_w_p = dets_p.sum(1).mean()
    print(f"mean det weight/shot: naive {shot_w_n:.4f} prod {shot_w_p:.4f} "
          f"(ratio {shot_w_p / max(shot_w_n, eps):.4f})")


def mode_decode(args):
    """Decode A/B: identical decode chain, two detector sources."""
    import jax
    import jax.numpy as jnp

    from parity import make_circuit_decoders
    from qldpc_fault_tolerance_tpu.sim import CodeSimulator_Circuit

    p, cycles = args.p, args.cycles
    from qldpc_fault_tolerance_tpu.codes import hgp, ring_code
    code = hgp(ring_code(args.d), ring_code(args.d), name=f"toric_d{args.d}")
    error_params = {"p_i": 0, "p_state_p": 0, "p_m": 0, "p_CX": p,
                    "p_idling_gate": 0}
    dec1, dec2 = make_circuit_decoders(code, p)
    sim = CodeSimulator_Circuit(code=code, decoder1_z=dec1, decoder2_z=dec2,
                                p=p, num_cycles=cycles,
                                error_params=error_params, seed=0,
                                batch_size=args.shots)
    sim._generate_circuit()
    naive = NaiveFrameSim(Circuit(str(sim.circuit)))
    rng = np.random.default_rng(11)
    parts = []
    chunk = 10000
    for i in range(0, args.shots, chunk):
        parts.append(naive.run(min(chunk, args.shots - i), rng))
    dets_n = np.concatenate([p_[0] for p_ in parts])
    obs_n = np.concatenate([p_[1] for p_ in parts])

    from qldpc_fault_tolerance_tpu.sim.circuit import _decode_rounds_given

    f_naive = 0
    for i in range(0, args.shots, chunk):
        n_b = min(chunk, args.shots - i)
        pending = _decode_rounds_given(
            sim._cfg(n_b), sim._dev_state,
            jnp.asarray(dets_n[i:i + n_b]), jnp.asarray(obs_n[i:i + n_b]))
        f_naive += int(np.asarray(sim._finish_batch(pending)).sum())

    f_prod = 0
    for i in range(0, args.shots, chunk):
        n_b = min(chunk, args.shots - i)
        f_prod += int(sim.run_batch(jax.random.PRNGKey(500 + i), n_b).sum())
    print(f"toric d{args.d} cycles={cycles} p={p} shots={args.shots}")
    print(f"failures: production-sampler {f_prod} "
          f"({f_prod / args.shots:.5f}) vs naive-stim-sim {f_naive} "
          f"({f_naive / args.shots:.5f})")
    lo, hi = sorted((f_prod, f_naive))
    sigma = np.sqrt(max(hi, 1))
    print(f"|delta|/sigma ~ {abs(f_prod - f_naive) / sigma:.2f}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["faults", "moments", "decode"],
                    default="faults")
    ap.add_argument("--d", type=int, default=5)
    ap.add_argument("--cycles", type=int, default=10)
    ap.add_argument("--p", type=float, default=2e-3)
    ap.add_argument("--shots", type=int, default=100000)
    args = ap.parse_args()
    {"faults": mode_faults, "moments": mode_moments,
     "decode": mode_decode}[args.mode](args)


if __name__ == "__main__":
    main()
