#!/usr/bin/env python
"""Run the multi-host serving fabric's fleet router over N decode hosts.

    python scripts/fleet_router.py --port 9200 \
        --host-addr a=10.0.0.1:9000 --host-addr b=10.0.0.2:9000 \
        --ops a=http://10.0.0.1:9001 --ops b=http://10.0.0.2:9001 \
        --family fam0=hgp_rep3,hgp_rep4

Clients connect to the router exactly as they would to one host (same
wire protocol, same DecodeClient).  Frames route to each bucket family's
owner host (consistent hash), the answered journal replicates to the
family successor, and when the federation gateway's host-down deadman
fires the family hands off exactly-once (see
qldpc_fault_tolerance_tpu.serve.router).  The router's ops view —
gateway merge + placement table + last-handoff ages — serves on
``--ops-port`` (/metrics /healthz /varz /alertz).
"""
from __future__ import annotations

import argparse
import sys
import threading

from fleet_gateway import parse_targets


def parse_pairs(specs, what: str) -> dict:
    out = {}
    for spec in specs:
        if "=" not in spec:
            raise SystemExit(f"bad --{what} {spec!r}: expected LABEL=VALUE")
        label, value = spec.split("=", 1)
        if label in out:
            raise SystemExit(f"duplicate --{what} label {label!r}")
        out[label] = value
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host-addr", action="append", default=[],
                    metavar="LABEL=HOST:PORT", dest="host_addrs",
                    help="decode-server address of one host (repeatable)")
    ap.add_argument("--ops", action="append", default=[],
                    metavar="LABEL=URL", dest="ops_targets",
                    help="ops endpoint of one host (repeatable; labels "
                         "must match --host-addr)")
    ap.add_argument("--family", action="append", default=[],
                    metavar="KEY=SESSION[,SESSION...]", dest="families",
                    help="one bucket family's session names (repeatable)")
    ap.add_argument("--profile", action="append", default=[],
                    metavar="NAME=SESSION", dest="profiles",
                    help="stream profile -> session mapping (repeatable)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="client-facing router port")
    ap.add_argument("--ops-port", type=int, default=0,
                    help="router ops-view port")
    ap.add_argument("--interval", type=float, default=5.0,
                    help="gateway scrape interval, seconds")
    ap.add_argument("--down-after", type=float, default=None,
                    help="host-down deadman window (default 3 intervals)")
    ap.add_argument("--control-interval", type=float, default=0.25,
                    help="router control-loop tick, seconds")
    ap.add_argument("--telemetry-jsonl", default=None,
                    help="enable telemetry with this JSONL sink")
    args = ap.parse_args(argv)
    if not args.host_addrs:
        ap.error("at least one --host-addr is required")
    if not args.families:
        ap.error("at least one --family is required")

    from qldpc_fault_tolerance_tpu.serve import fleet, router
    from qldpc_fault_tolerance_tpu.utils import telemetry

    if args.telemetry_jsonl:
        telemetry.enable(args.telemetry_jsonl)

    hosts = {}
    for label, addr in parse_pairs(args.host_addrs, "host-addr").items():
        hp, _, port = addr.rpartition(":")
        if not hp or not port.isdigit():
            raise SystemExit(f"bad --host-addr {addr!r}: expected HOST:PORT")
        hosts[label] = (hp, int(port))
    families = {key: [s for s in val.split(",") if s]
                for key, val in parse_pairs(args.families,
                                            "family").items()}
    profiles = parse_pairs(args.profiles, "profile")

    ops_targets = parse_targets(args.ops_targets)
    missing = sorted(set(ops_targets) - set(hosts))
    if missing:
        raise SystemExit(f"--ops labels {missing} have no --host-addr")
    gw = (fleet.FleetGateway(ops_targets, interval_s=args.interval,
                             down_after_s=args.down_after)
          if ops_targets else None)
    rt = router.FleetRouter(hosts, families, profiles=profiles,
                            gateway=gw,
                            control_interval_s=args.control_interval)
    handle = router.start_router_thread(rt)
    ops_handle = None
    if gw is not None:
        ops_handle = router.start_router_ops_thread(
            rt, gw, host=args.host, port=args.ops_port, scrape=True)
    host, port = handle.address
    fams = ", ".join(f"{k}({len(v)})" for k, v in sorted(families.items()))
    print(f"fleet router on {host}:{port} — {len(hosts)} hosts, "
          f"families: {fams}" + (
              "; ops view on http://{}:{}".format(*ops_handle.address)
              if ops_handle else
              " (no --ops targets: handoff deadman DISABLED)")
          + "; Ctrl-C to stop")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        handle.stop()
        if ops_handle is not None:
            ops_handle.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
