"""Execute a reference notebook unmodified through the compat layer.

This is the BASELINE.json north-star contract ("notebooks run unmodified"):
the notebook's own cells — written against the reference's module names and
the native ldpc/bposd/stim packages — execute against this framework via
``compat.install()``, which is injected as a bootstrap cell (the only
addition; no reference cell is edited).

Usage:
  python scripts/run_reference_notebook.py /root/reference/SpaceTimeDecodingDemo.ipynb
  python scripts/run_reference_notebook.py <path.ipynb> --out examples/executed/

The executed copy (with fresh outputs) is written next to --out for the
record.  For SpaceTimeDecodingDemo the script additionally checks cell 3's
WER against the notebook's own saved output (0.000193 at 10k samples) within
binomial error.
"""
import argparse
import copy
import os
import re
import sys

import nbformat
from nbclient import NotebookClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BOOTSTRAP = f"""\
import sys
sys.path.insert(0, {REPO!r})
import qldpc_fault_tolerance_tpu.compat as _compat
_compat.install()
import matplotlib
matplotlib.use("Agg")
"""


def run(path: str, out_dir: str, timeout: int = 3600, cells=None,
        append_source: str | None = None, allow_scratch_errors: bool = False):
    """``cells``: optional list of cell indices to keep (a "trimmed" run —
    cells are untouched, just selected).  ``append_source``: optional extra
    driver cell appended at the end.

    ``allow_scratch_errors``: execute every cell even if one errors, then
    enforce the contract that matters: every cell the author's saved
    session actually executed (i.e. that HAS saved outputs) must run
    cleanly here.  The checkpoints contain leftover scratch cells with no
    saved outputs (e.g. Single-Shot cell 13, commented-out plotting against
    variables from another session) that error for the reference library
    too — those may error without failing the run, and the mismatch is
    reported cell by cell."""
    nb = nbformat.read(path, as_version=4)
    executed = copy.deepcopy(nb)
    if cells is not None:
        keep = set(cells)
        executed.cells = [c for i, c in enumerate(executed.cells) if i in keep]
    kept_orig = (list(nb.cells) if cells is None
                 else [c for i, c in enumerate(nb.cells) if i in set(cells)])
    if append_source:
        executed.cells.append(nbformat.v4.new_code_cell(append_source))
    boot = nbformat.v4.new_code_cell(BOOTSTRAP)
    # nbformat >=5.1 requires ids; new_code_cell provides one
    executed.cells.insert(0, boot)

    client = NotebookClient(
        executed, timeout=timeout, kernel_name="python3",
        resources={"metadata": {"path": REPO}},
        allow_errors=allow_scratch_errors,
    )
    client.execute()

    os.makedirs(out_dir, exist_ok=True)
    out_path = os.path.join(
        out_dir, os.path.basename(path).replace(".ipynb", ".executed.ipynb")
    )
    nbformat.write(executed, out_path)
    print(f"executed notebook written to {out_path}")

    if allow_scratch_errors:
        all_src = "\n".join("".join(c.get("source", ""))
                            for c in nb.cells)
        bad = []
        scratch_errs = 0
        stale_errs = 0
        for orig, cell in zip(kept_orig, executed.cells[1:]):
            errs = [o for o in cell.get("outputs", [])
                    if o.get("output_type") == "error"]
            if not errs:
                continue
            ename = errs[0].get("ename")
            evalue = str(errs[0].get("evalue"))
            if not orig.get("outputs"):
                scratch_errs += 1
                continue
            # stale-session cells: a NameError on a name that is defined
            # NOWHERE in the notebook (e.g. Threshold cell 14's
            # CodeFamilyThreshold) cannot execute against any version of
            # the reference either — the author's saved output came from an
            # older kernel session.  Reported, not fatal.
            m = re.match(r"name '(\w+)' is not defined", evalue)
            if ename == "NameError" and m and \
                    f"def {m.group(1)}" not in all_src and \
                    f"{m.group(1)} =" not in all_src:
                stale_errs += 1
                print(f"stale-session cell (name {m.group(1)!r} defined "
                      f"nowhere in the notebook): error tolerated")
                continue
            bad.append((ename, evalue[:120]))
        print(f"cells executed: {len(executed.cells) - 1}; errors in "
              f"author-executed cells: {len(bad)}; stale-session cells: "
              f"{stale_errs}; scratch cells (no saved outputs): "
              f"{scratch_errs}")
        assert not bad, (
            "cells with saved reference outputs errored: " + repr(bad)
        )
    return executed


def check_demo_wer(executed) -> None:
    """SpaceTimeDecodingDemo cell 3 (index 4 after bootstrap) returns the
    WER; the reference's saved output is 0.00019299... at 10000 samples."""
    import numpy as np

    cell = executed.cells[4]
    outs = [o for o in cell.get("outputs", []) if o.get("data")]
    val = float(outs[0]["data"]["text/plain"])
    published = 0.00019299501269032238
    # invert the per-cycle/per-qubit mapping back to a raw failure rate to
    # get the binomial error bar (K=2, 13 cycles, 10k samples)
    def raw(wer, K=2, cycles=13):
        plq = 1 - (1 - 2 * wer) ** cycles
        plq /= 2
        return 1 - (1 - plq) ** K

    n = 10000
    p_pub = raw(published)
    p_meas = raw(val)
    sigma = np.sqrt(p_pub * (1 - p_pub) / n)
    z = abs(p_meas - p_pub) / sigma
    print(f"demo WER: measured {val:.3e} vs published {published:.3e} "
          f"(z = {z:.2f} on raw failure rate)")
    assert z < 4.0, "demo WER inconsistent with the reference's saved output"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("notebook")
    ap.add_argument("--out", default=os.path.join(REPO, "examples", "executed"))
    ap.add_argument("--timeout", type=int, default=3600)
    ap.add_argument("--cells", type=int, nargs="*", default=None,
                    help="cell indices to keep (trimmed run)")
    ap.add_argument("--append-cell", default=None,
                    help="extra driver cell source appended at the end")
    ap.add_argument("--allow-scratch-errors", action="store_true",
                    help="keep executing past errors, then require that "
                         "only never-executed scratch cells errored")
    args = ap.parse_args()
    cells = args.cells if args.cells else None  # bare --cells = full run
    executed = run(args.notebook, args.out, args.timeout, cells=cells,
                   append_source=args.append_cell,
                   allow_scratch_errors=args.allow_scratch_errors)
    if re.search(r"SpaceTimeDecodingDemo", args.notebook) and cells is None:
        check_demo_wer(executed)


if __name__ == "__main__":
    main()
