"""Thin shim: the slope-based tunnel-safe timer moved to
``qldpc_fault_tolerance_tpu.utils.profiling.per_call_seconds`` (the ISSUE-6
performance-attribution subsystem).  Import from there; this module stays
so existing notebooks/scripts keep working.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qldpc_fault_tolerance_tpu.utils.profiling import (  # noqa: E402,F401
    per_call_seconds,
)

__all__ = ["per_call_seconds"]
