"""Reliable kernel timing through the tunneled TPU.

``jax.block_until_ready`` does not reliably wait for execution through the
axon tunnel, and a host fetch pays ~110ms round-trip latency.  So: launch
``r`` chained async dispatches, force completion with a scalar fetch, and take
the slope between two rep counts — the fixed tunnel latency cancels.
"""
import time

import jax
import jax.numpy as jnp

__all__ = ["per_call_seconds"]


def _fetch(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(jnp.asarray(leaf).reshape(-1)[0])


def _run(fn, args, reps):
    t0 = time.perf_counter()
    out = None
    for _ in range(reps):
        out = fn(*args)
    _fetch(out)
    return time.perf_counter() - t0


def per_call_seconds(fn, *args, lo=3, hi=23, trials=3):
    """Median slope-based per-call wall time of ``fn(*args)``."""
    _run(fn, args, 1)  # warm / compile
    slopes = []
    for _ in range(trials):
        t_lo = _run(fn, args, lo)
        t_hi = _run(fn, args, hi)
        slopes.append((t_hi - t_lo) / (hi - lo))
    slopes.sort()
    return slopes[len(slopes) // 2]
