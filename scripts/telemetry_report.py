"""Render a telemetry JSONL run (utils.telemetry JsonlSink) into a human
summary table.

    python scripts/telemetry_report.py RUN.jsonl            # text table
    python scripts/telemetry_report.py RUN.jsonl --json     # summary json
    python scripts/telemetry_report.py RUN.jsonl --prometheus
    python scripts/telemetry_report.py RUN.jsonl --follow   # live re-render
    python scripts/telemetry_report.py RUN.jsonl --traces   # slow/errored
    python scripts/telemetry_report.py RUN.jsonl --trace ID # one span tree

The stream is the one ``telemetry.enable(jsonl_path=...)`` (or
``QLDPC_TELEMETRY_JSONL=...``) writes: ``wer_run`` / ``cell_done`` events as
the run progresses and a final ``snapshot`` event carrying the full metrics
registry + compile stats (``telemetry.write_snapshot_event`` /
``telemetry.session``).  Metrics are cumulative, so the LAST snapshot wins.

``--follow`` tails an ACTIVE sink: new complete lines are parsed
incrementally (a partially-flushed tail line is left for the next poll)
and the table re-renders in place every ``--interval`` seconds until
Ctrl-C — no need to wait for the run to finish.

``--traces`` / ``--trace ID`` (ISSUE 11) query the per-request ``trace``
events the serve stack emits (utils.tracing): ``--traces`` lists recent
traces newest-first (``--slow-ms`` / ``--errored`` filter like
``/tracez``); ``--trace ID`` renders one request's full span tree —
queue_wait / batch_assemble / pad / device_decode / slice / respond under
its serve.request root — from the JSONL alone.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_events(path: str) -> list[dict]:
    """Parse one JSONL stream; unparseable lines are skipped (a crashed run
    may truncate its last line) but counted."""
    events, bad = [], 0
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                bad += 1
    if bad:
        print(f"warning: skipped {bad} unparseable line(s)", file=sys.stderr)
    return events


class FollowReader:
    """Incremental JSONL reader for ``--follow``: each ``poll()`` returns
    the events appended since the last poll.  Only COMPLETE lines are
    consumed — a torn tail (the writer's in-flight flush, or a crash)
    stays buffered until its newline arrives, so a mid-write poll never
    misparses or drops an event.  A file that does not exist yet simply
    yields nothing (the run may not have opened its sink)."""

    def __init__(self, path: str):
        self.path = str(path)
        self._offset = 0

    def poll(self) -> list[dict]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size < self._offset:  # truncated/rotated: start over
            self._offset = 0
        if size == self._offset:
            return []
        with open(self.path, "rb") as fh:
            fh.seek(self._offset)
            chunk = fh.read(size - self._offset)
        end = chunk.rfind(b"\n")
        if end < 0:
            return []  # no complete line yet
        self._offset += end + 1
        events = []
        for line in chunk[: end + 1].splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line.decode("utf-8")))
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue  # torn line from a crashed writer
        return events


def follow(path: str, interval: float = 1.0, *, render_fn=None,
           out=None, max_polls=None) -> int:
    """Tail ``path`` and re-render the summary table on new events.
    Aggregation is INCREMENTAL — each poll folds only the fresh events
    into a running state (metrics are cumulative and the last snapshot
    wins, so nothing needs the full history), so a multi-hour sink costs
    O(new events) per tick and bounded memory.  ``max_polls`` bounds the
    loop for tests; interactive use runs until Ctrl-C."""
    out = out or sys.stdout
    render_fn = render_fn or (lambda s: render(s, title=os.path.basename(
        path) + " (following)"))
    reader = FollowReader(path)
    state = new_fold_state()
    seen_any = False
    polls = 0
    try:
        while max_polls is None or polls < max_polls:
            fresh = reader.poll()
            polls += 1
            if fresh or polls == 1:
                fold_events(state, fresh)
                seen_any = seen_any or bool(fresh)
                if seen_any:
                    out.write("\x1b[2J\x1b[H" if out.isatty() else "")
                    out.write(render_fn(summary_from_state(state)) + "\n")
                    out.flush()
            if max_polls is None or polls < max_polls:
                time.sleep(interval)
    except KeyboardInterrupt:
        pass
    return 0


def _metric(snap: dict, name: str, field: str = "value", default=0):
    return snap.get(name, {}).get(field, default)


def _hist_quantile(m: dict, q: float):
    """Estimated quantile of a fixed-bucket histogram (linear interpolation
    within the bucket; the overflow bucket reports its lower edge).  The
    registry histograms don't keep raw samples, so this is the honest
    bucket-resolution estimate — exact per-sample percentiles live in
    utils.observability.timings() for stage timers."""
    buckets, counts, total = m.get("buckets"), m.get("counts"), m.get("count")
    if not buckets or not counts or not total:
        return None
    target = q * total
    acc = 0.0
    lo = 0.0
    for edge, c in zip(buckets, counts):
        if acc + c >= target and c:
            frac = (target - acc) / c
            return lo + (edge - lo) * frac
        acc += c
        lo = edge
    return buckets[-1]  # overflow: lower edge of the open bucket


def new_fold_state() -> dict:
    """Empty incremental-aggregation state for ``fold_events`` (metrics
    are cumulative and the LAST snapshot wins, so the fold only needs the
    kind counts, the ts range, and the latest snapshot event)."""
    return {"kinds": {}, "ts_min": None, "ts_max": None, "snapshot": None}


def fold_events(state: dict, events: list[dict]) -> dict:
    """Fold a batch of events into ``state`` (in place; returns it)."""
    kinds = state["kinds"]
    for e in events:
        k = e.get("kind", "?")
        kinds[k] = kinds.get(k, 0) + 1
        ts = e.get("ts")
        if isinstance(ts, (int, float)):
            state["ts_min"] = ts if state["ts_min"] is None \
                else min(state["ts_min"], ts)
            state["ts_max"] = ts if state["ts_max"] is None \
                else max(state["ts_max"], ts)
        if k == "snapshot":
            state["snapshot"] = e
    return state


def summarize(events: list[dict]) -> dict:
    """Aggregate an event stream into one summary dict (the --json output;
    the text table renders from this)."""
    return summary_from_state(fold_events(new_fold_state(), events))


def summary_from_state(state: dict) -> dict:
    kinds = state["kinds"]
    snapshot_event = state["snapshot"]
    snap = snapshot_event.get("metrics", {}) if snapshot_event else {}
    compile_stats = snapshot_event.get("compile", {}) if snapshot_event \
        else {}
    wall = (round(state["ts_max"] - state["ts_min"], 3)
            if state["ts_min"] is not None
            and state["ts_max"] is not None else 0.0)

    bp_shots = _metric(snap, "bp.shots")
    bp_conv = _metric(snap, "bp.converged")
    iters = snap.get("bp.iterations", {})
    osd_host_shots = _metric(snap, "osd.shots")
    osd_dev_shots = _metric(snap, "osd.device_shots")
    lat = snap.get("serve.latency_s", {})
    occ = snap.get("serve.batch_occupancy", {})
    serve_requests = _metric(snap, "serve.requests")
    serve = {
        "requests": serve_requests,
        "shots": _metric(snap, "serve.shots"),
        "batches": _metric(snap, "serve.batches"),
        "padded_shots": _metric(snap, "serve.padded_shots"),
        "errors": _metric(snap, "serve.errors"),
        "queue_depth_max": _metric(snap, "serve.queue_depth", "max"),
        "sessions": _metric(snap, "serve.sessions"),
        "session_compiles": _metric(snap, "serve.session.compiles"),
        "session_evictions": _metric(snap, "serve.session.evictions"),
        "occupancy_mean": (round(occ["mean"], 4)
                           if occ.get("mean") is not None else None),
        "latency_p50_s": _hist_quantile(lat, 0.50),
        "latency_p99_s": _hist_quantile(lat, 0.99),
        # wire accounting (ISSUE 15): framed bytes both ways + the last
        # negotiated codec, and the cross-session fused-dispatch counters
        "bytes_rx": _metric(snap, "serve.bytes_rx"),
        "bytes_tx": _metric(snap, "serve.bytes_tx"),
        "wire_codec_version": _metric(snap, "wire.codec_version") or None,
        "fused_dispatches": _metric(snap, "serve.fused.dispatches"),
        "fused_fallbacks": _metric(snap, "serve.fused.fallbacks"),
        "tenants": {
            name[len("serve.tenant."):-len(".requests")]: m.get("value", 0)
            for name, m in snap.items()
            if name.startswith("serve.tenant.")
            and name.endswith(".requests")
        },
    }
    # streaming decode counters (ISSUE 16): rendered, not silently dropped
    stream = {
        "opens": _metric(snap, "stream.opens"),
        "commits": _metric(snap, "stream.commits"),
        "cycles": _metric(snap, "stream.cycles"),
        "replays": _metric(snap, "stream.replays"),
        "shed": _metric(snap, "stream.shed"),
        "protocol_errors": _metric(snap, "stream.protocol_errors"),
        "open_streams": _metric(snap, "stream.open_streams"),
    }
    spans = {
        name[len("span."):-len(".seconds")]: m
        for name, m in snap.items()
        if name.startswith("span.") and m.get("type") == "histogram"
    }
    return {
        "events": kinds,
        "wall_s": wall,
        "shots": _metric(snap, "sim.shots"),
        "failures": _metric(snap, "sim.failures"),
        "runs": _metric(snap, "sim.runs"),
        "sweep_cells": _metric(snap, "sweep.cells"),
        "dispatches": _metric(snap, "driver.dispatches"),
        "batches": _metric(snap, "driver.batches"),
        "early_stops": _metric(snap, "driver.early_stops"),
        "drain_depth_max": _metric(snap, "driver.drain_depth", "max"),
        "bp": {
            "shots": bp_shots,
            "converged": bp_conv,
            "converged_fraction": (round(bp_conv / bp_shots, 6)
                                   if bp_shots else None),
            "iterations_mean": iters.get("mean"),
            "iterations_buckets": iters.get("buckets"),
            "iterations_counts": iters.get("counts"),
        },
        "osd": {
            "invocations": _metric(snap, "osd.invocations"),
            "host_shots": osd_host_shots,
            "device_shots": osd_dev_shots,
            "shots": osd_host_shots + osd_dev_shots,
            "host_round_trips": _metric(snap, "osd.host_round_trips"),
        },
        "serve": serve,
        "stream": stream,
        "jax": {
            "retraces": compile_stats.get(
                "jax.retraces", _metric(snap, "jax.retraces")),
            "lowerings": compile_stats.get(
                "jax.lowerings", _metric(snap, "jax.lowerings")),
            "backend_compiles": compile_stats.get(
                "jax.backend_compiles", _metric(snap, "jax.backend_compiles")),
            "backend_compile_s": round(
                _metric(snap, "jax.backend_compiles.seconds"), 3),
            "retrace_source": compile_stats.get("source"),
        },
        "spans": {
            name: {"count": m["count"], "total_s": round(m["sum"], 4),
                   "mean_s": (round(m["sum"] / m["count"], 5)
                              if m["count"] else None),
                   "p50_s": (round(_hist_quantile(m, 0.50), 5)
                             if _hist_quantile(m, 0.50) is not None
                             else None),
                   "p95_s": (round(_hist_quantile(m, 0.95), 5)
                             if _hist_quantile(m, 0.95) is not None
                             else None)}
            for name, m in sorted(spans.items())
        },
        "snapshot": snap,
    }


def render_trace_tree(spans: list[dict]) -> str:
    """One trace's spans as an indented tree (the --trace view): name,
    duration, amortization factor and error per span."""
    from qldpc_fault_tolerance_tpu.utils import tracing

    tree = tracing.trace_tree(spans)

    def _line(node, depth):
        s = node["span"]
        parts = [f"{'  ' * depth}{s.get('name')}",
                 f"{1e3 * float(s.get('dur_s', 0.0)):.3f} ms"]
        if s.get("amortized_over", 1) not in (None, 1):
            parts.append(f"(amortized /{s['amortized_over']})")
        if s.get("ok") is False or s.get("error"):
            parts.append(f"ERROR: {s.get('error', '?')}")
        rows.append("  ".join(parts))
        for child in sorted(node["children"],
                            key=lambda n: n["span"].get("ts") or 0.0):
            _line(child, depth + 1)

    rows: list[str] = []
    for root in tree["roots"]:
        _line(root, 0)
    return "\n".join(rows) if rows else "(no spans)"


def render_traces(events: list[dict], *, limit: int = 50,
                  slow_s=None, errored_only: bool = False) -> str:
    """Recent traces, newest-first (the --traces view)."""
    from qldpc_fault_tolerance_tpu.utils import tracing

    rows = tracing.trace_summaries(events, limit=limit, slow_s=slow_s,
                                   errored_only=errored_only)
    if not rows:
        return "(no trace events)"
    L = [f"{'trace_id':<34}{'spans':>6}{'max_ms':>10}{'total_ms':>10}"
         f"  names"]
    for r in rows:
        L.append(f"{r['trace_id']:<34}{r['spans']:>6}"
                 f"{1e3 * r['max_dur_s']:>10.3f}"
                 f"{1e3 * r['total_dur_s']:>10.3f}"
                 f"  {','.join(r['names'])}"
                 + ("  [ERRORED]" if r["errored"] else ""))
    return "\n".join(L)


def _bar(n: int, peak: int, width: int = 30) -> str:
    return "#" * max(1 if n else 0, round(width * n / peak)) if peak else ""


def render(summary: dict, title: str = "") -> str:
    """The human table."""
    s = summary
    L = [f"== qldpc telemetry report{': ' + title if title else ''} =="]
    ev = ", ".join(f"{v} {k}" for k, v in sorted(s["events"].items()))
    L.append(f"events: {ev}   (span {s['wall_s']}s wall)")
    L.append("")
    L.append("-- runs --")
    rows = [
        ("shots", s["shots"]), ("failures", s["failures"]),
        ("wer runs", s["runs"]), ("sweep cells", s["sweep_cells"]),
        ("dispatches", s["dispatches"]), ("batches", s["batches"]),
        ("early stops", s["early_stops"]),
        ("drain depth (max)", s["drain_depth_max"]),
    ]
    if s["shots"]:
        rows.insert(2, ("failure fraction",
                        round(s["failures"] / s["shots"], 6)))
    for k, v in rows:
        L.append(f"  {k:<22}{v}")
    bp = s["bp"]
    L.append("-- bp decoder --")
    L.append(f"  {'decoder shots':<22}{bp['shots']}")
    if bp["shots"]:
        L.append(f"  {'converged':<22}{bp['converged']}"
                 f"  ({100 * bp['converged_fraction']:.2f}%)")
        if bp["iterations_mean"] is not None:
            L.append(f"  iterations to convergence "
                     f"(mean {bp['iterations_mean']:.2f}):")
            buckets = bp["iterations_buckets"] or []
            counts = bp["iterations_counts"] or []
            peak = max(counts) if counts else 0
            labels = [f"<={b}" for b in buckets] + [f">{buckets[-1]}"
                                                    if buckets else ">"]
            for lab, n in zip(labels, counts):
                if n:
                    L.append(f"    {lab:>6} {n:>10}  {_bar(n, peak)}")
    srv = s.get("serve") or {}
    if srv.get("requests"):
        L.append("-- serve (decode service) --")
        L.append(f"  {'requests':<22}{srv['requests']}"
                 f"  ({srv['errors']} failed)")
        L.append(f"  {'shots':<22}{srv['shots']}"
                 f"  (+{srv['padded_shots']} pad)")
        L.append(f"  {'batches':<22}{srv['batches']}"
                 + (f"  (occupancy {srv['occupancy_mean']:.2f})"
                    if srv.get("occupancy_mean") is not None else ""))
        p50, p99 = srv.get("latency_p50_s"), srv.get("latency_p99_s")
        if p50 is not None:
            L.append(f"  {'latency p50/p99':<22}"
                     f"{1e3 * p50:.1f} / {1e3 * p99:.1f} ms")
        L.append(f"  {'queue depth (max)':<22}{srv['queue_depth_max']}")
        if srv.get("bytes_rx") or srv.get("bytes_tx"):
            codec = srv.get("wire_codec_version")
            L.append(f"  {'wire bytes rx/tx':<22}"
                     f"{srv['bytes_rx']} / {srv['bytes_tx']}"
                     + (f"  (codec v{codec})" if codec else ""))
        if srv.get("fused_dispatches") or srv.get("fused_fallbacks"):
            L.append(f"  {'fused dispatches':<22}{srv['fused_dispatches']}"
                     f"  ({srv['fused_fallbacks']} fallbacks)")
        L.append(f"  {'sessions':<22}{srv['sessions']}"
                 f"  ({srv['session_compiles']} compiles, "
                 f"{srv['session_evictions']} evictions)")
        for tenant, n in sorted(srv.get("tenants", {}).items()):
            L.append(f"  {'tenant ' + tenant:<22}{n}")
    stm = s.get("stream") or {}
    if stm.get("opens") or stm.get("commits"):
        L.append("-- stream (overlap-commit decode) --")
        L.append(f"  {'streams opened':<22}{stm['opens']}"
                 f"  ({stm['open_streams']} still open)")
        L.append(f"  {'windows committed':<22}{stm['commits']}"
                 f"  ({stm['cycles']} cycles)")
        if stm.get("replays"):
            L.append(f"  {'replayed seqs':<22}{stm['replays']}")
        if stm.get("shed") or stm.get("protocol_errors"):
            L.append(f"  {'shed / proto errors':<22}{stm['shed']}"
                     f" / {stm['protocol_errors']}")
    osd = s["osd"]
    L.append("-- osd --")
    L.append(f"  {'invocations':<22}{osd['invocations']}")
    L.append(f"  {'shots':<22}{osd['shots']}"
             f"  (host {osd['host_shots']}, device {osd['device_shots']})")
    L.append(f"  {'host round-trips':<22}{osd['host_round_trips']}")
    j = s["jax"]
    L.append("-- jax compile --")
    L.append(f"  retraces {j['retraces']}   lowerings {j['lowerings']}   "
             f"backend compiles {j['backend_compiles']} "
             f"({j['backend_compile_s']}s)"
             + (f"   [source: {j['retrace_source']}]"
                if j.get("retrace_source") else ""))
    if s["spans"]:
        L.append("-- spans --")
        w = max(len(n) for n in s["spans"]) + 2
        L.append(f"  {'name':<{w}}{'count':>7}{'total_s':>12}{'mean_s':>12}"
                 f"{'p50_s':>12}{'p95_s':>12}")
        for name, m in s["spans"].items():
            L.append(f"  {name:<{w}}{m['count']:>7}{m['total_s']:>12}"
                     f"{m['mean_s']:>12}"
                     f"{m.get('p50_s') if m.get('p50_s') is not None else '-':>12}"
                     f"{m.get('p95_s') if m.get('p95_s') is not None else '-':>12}")
    return "\n".join(L)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="telemetry JSONL stream to render")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as json instead of the table")
    ap.add_argument("--prometheus", action="store_true",
                    help="emit the final snapshot in Prometheus text format")
    ap.add_argument("--follow", action="store_true",
                    help="tail an active sink and re-render incrementally "
                         "(Ctrl-C to stop)")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="--follow poll interval in seconds (default 1)")
    ap.add_argument("--traces", action="store_true",
                    help="list recent traces (newest-first) from the "
                         "stream's trace events")
    ap.add_argument("--trace", metavar="ID",
                    help="render one trace id's span tree")
    ap.add_argument("--slow-ms", type=float, default=None,
                    help="--traces: only traces with a span at least this "
                         "slow")
    ap.add_argument("--errored", action="store_true",
                    help="--traces: only traces with an errored span")
    args = ap.parse_args(argv)

    if args.follow:
        if args.traces or args.trace:
            # silently rendering the summary instead of the asked-for
            # trace view would be the wrong output with no explanation
            ap.error("--traces/--trace are not supported with --follow; "
                     "run them against the stream without --follow")
        return follow(args.jsonl, args.interval)

    events = load_events(args.jsonl)
    if not events:
        print(f"no events in {args.jsonl}", file=sys.stderr)
        return 1
    if args.trace:
        from qldpc_fault_tolerance_tpu.utils import tracing

        spans = tracing.traces_from_records(events).get(args.trace, [])
        if not spans:
            print(f"no spans for trace {args.trace!r}", file=sys.stderr)
            return 1
        print(render_trace_tree(spans))
        return 0
    if args.traces:
        print(render_traces(
            events, slow_s=(None if args.slow_ms is None
                            else args.slow_ms / 1e3),
            errored_only=args.errored))
        return 0
    summary = summarize(events)
    if args.prometheus:
        from qldpc_fault_tolerance_tpu.utils import telemetry

        sys.stdout.write(telemetry.prometheus_text(summary["snapshot"]))
        return 0
    if args.json:
        out = dict(summary)
        out.pop("snapshot")  # the raw registry dump is --prometheus/json-able
        print(json.dumps(out, indent=1, default=str))
        return 0
    print(render(summary, title=os.path.basename(args.jsonl)))
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:  # e.g. `... | head` — not an error
        raise SystemExit(0)
