"""Render a telemetry JSONL run (utils.telemetry JsonlSink) into a human
summary table.

    python scripts/telemetry_report.py RUN.jsonl            # text table
    python scripts/telemetry_report.py RUN.jsonl --json     # summary json
    python scripts/telemetry_report.py RUN.jsonl --prometheus
    python scripts/telemetry_report.py RUN.jsonl --follow   # live re-render
    python scripts/telemetry_report.py RUN.jsonl --traces   # slow/errored
    python scripts/telemetry_report.py RUN.jsonl --trace ID # one span tree
    python scripts/telemetry_report.py RUN.jsonl --rates 60 # windowed rates
    python scripts/telemetry_report.py RUN.jsonl --fleet http://gw:9100
    python scripts/telemetry_report.py --fleet http://gw:9100  # fleet only

The stream is the one ``telemetry.enable(jsonl_path=...)`` (or
``QLDPC_TELEMETRY_JSONL=...``) writes: ``wer_run`` / ``cell_done`` events as
the run progresses and a final ``snapshot`` event carrying the full metrics
registry + compile stats (``telemetry.write_snapshot_event`` /
``telemetry.session``).  Metrics are cumulative, so the LAST snapshot wins.

``--follow`` tails an ACTIVE sink: new complete lines are parsed
incrementally (a partially-flushed tail line is left for the next poll)
and the table re-renders in place every ``--interval`` seconds until
Ctrl-C — no need to wait for the run to finish.

``--traces`` / ``--trace ID`` (ISSUE 11) query the per-request ``trace``
events the serve stack emits (utils.tracing): ``--traces`` lists recent
traces newest-first (``--slow-ms`` / ``--errored`` filter like
``/tracez``); ``--trace ID`` renders one request's full span tree —
queue_wait / batch_assemble / pad / device_decode / slice / respond under
its serve.request root — from the JSONL alone.

``--rates <window_s>`` (ISSUE 17) rebuilds a utils.timeseries.SeriesStore
from the stream's ``snapshot`` events (the Scraper's
``emit_snapshot_events=True`` writes one per tick) and renders counter
rates and windowed histogram p50/p99 over the trailing window.  With a
single snapshot there is nothing to difference, so lifetime averages are
shown and flagged.  ``--fleet <url-or-json>`` appends a fleet block from
a federation gateway (serve.fleet): per-host up/down, merged counter
totals, active alerts — pass the gateway base URL or a file holding its
``/varz`` JSON.  ``--fleet`` alone (no JSONL) renders just that block; a
gateway ``/healthz`` answering 503 (hosts down) still renders — the
degraded body is the interesting one.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_events(path: str) -> list[dict]:
    """Parse one JSONL stream; unparseable lines are skipped (a crashed run
    may truncate its last line) but counted."""
    events, bad = [], 0
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                bad += 1
    if bad:
        print(f"warning: skipped {bad} unparseable line(s)", file=sys.stderr)
    return events


class FollowReader:
    """Incremental JSONL reader for ``--follow``: each ``poll()`` returns
    the events appended since the last poll.  Only COMPLETE lines are
    consumed — a torn tail (the writer's in-flight flush, or a crash)
    stays buffered until its newline arrives, so a mid-write poll never
    misparses or drops an event.  A file that does not exist yet simply
    yields nothing (the run may not have opened its sink)."""

    def __init__(self, path: str):
        self.path = str(path)
        self._offset = 0

    def poll(self) -> list[dict]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size < self._offset:  # truncated/rotated: start over
            self._offset = 0
        if size == self._offset:
            return []
        with open(self.path, "rb") as fh:
            fh.seek(self._offset)
            chunk = fh.read(size - self._offset)
        end = chunk.rfind(b"\n")
        if end < 0:
            return []  # no complete line yet
        self._offset += end + 1
        events = []
        for line in chunk[: end + 1].splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line.decode("utf-8")))
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue  # torn line from a crashed writer
        return events


def follow(path: str, interval: float = 1.0, *, render_fn=None,
           out=None, max_polls=None) -> int:
    """Tail ``path`` and re-render the summary table on new events.
    Aggregation is INCREMENTAL — each poll folds only the fresh events
    into a running state (metrics are cumulative and the last snapshot
    wins, so nothing needs the full history), so a multi-hour sink costs
    O(new events) per tick and bounded memory.  ``max_polls`` bounds the
    loop for tests; interactive use runs until Ctrl-C."""
    out = out or sys.stdout
    render_fn = render_fn or (lambda s: render(s, title=os.path.basename(
        path) + " (following)"))
    reader = FollowReader(path)
    state = new_fold_state()
    seen_any = False
    polls = 0
    try:
        while max_polls is None or polls < max_polls:
            fresh = reader.poll()
            polls += 1
            if fresh or polls == 1:
                fold_events(state, fresh)
                seen_any = seen_any or bool(fresh)
                if seen_any:
                    out.write("\x1b[2J\x1b[H" if out.isatty() else "")
                    out.write(render_fn(summary_from_state(state)) + "\n")
                    out.flush()
            if max_polls is None or polls < max_polls:
                time.sleep(interval)
    except KeyboardInterrupt:
        pass
    return 0


def _metric(snap: dict, name: str, field: str = "value", default=0):
    return snap.get(name, {}).get(field, default)


def _hist_quantile(m: dict, q: float):
    """Estimated quantile of a fixed-bucket histogram (linear interpolation
    within the bucket; the overflow bucket reports its lower edge).  The
    registry histograms don't keep raw samples, so this is the honest
    bucket-resolution estimate — exact per-sample percentiles live in
    utils.observability.timings() for stage timers."""
    buckets, counts, total = m.get("buckets"), m.get("counts"), m.get("count")
    if not buckets or not counts or not total:
        return None
    target = q * total
    acc = 0.0
    lo = 0.0
    for edge, c in zip(buckets, counts):
        if acc + c >= target and c:
            frac = (target - acc) / c
            return lo + (edge - lo) * frac
        acc += c
        lo = edge
    return buckets[-1]  # overflow: lower edge of the open bucket


def new_fold_state() -> dict:
    """Empty incremental-aggregation state for ``fold_events`` (metrics
    are cumulative and the LAST snapshot wins, so the fold only needs the
    kind counts, the ts range, and the latest snapshot event)."""
    return {"kinds": {}, "ts_min": None, "ts_max": None, "snapshot": None}


def fold_events(state: dict, events: list[dict]) -> dict:
    """Fold a batch of events into ``state`` (in place; returns it)."""
    kinds = state["kinds"]
    for e in events:
        k = e.get("kind", "?")
        kinds[k] = kinds.get(k, 0) + 1
        ts = e.get("ts")
        if isinstance(ts, (int, float)):
            state["ts_min"] = ts if state["ts_min"] is None \
                else min(state["ts_min"], ts)
            state["ts_max"] = ts if state["ts_max"] is None \
                else max(state["ts_max"], ts)
        if k == "snapshot":
            state["snapshot"] = e
    return state


def summarize(events: list[dict]) -> dict:
    """Aggregate an event stream into one summary dict (the --json output;
    the text table renders from this)."""
    return summary_from_state(fold_events(new_fold_state(), events))


# a gauge whose last set is this much older than the snapshot it appears
# in is rendered STALE instead of silently showing its frozen value
STALE_GAUGE_AFTER_S = 60.0


def stale_gauges(snap: dict, snap_ts,
                 after_s: float = STALE_GAUGE_AFTER_S) -> dict:
    """{gauge_name: age_s} for gauges whose last-set stamp (``ts``, ISSUE
    17) lags the snapshot time by more than ``after_s``.  Gauges without a
    stamp (pre-v7 streams, never-set defaults) are not judged."""
    out = {}
    if not isinstance(snap_ts, (int, float)):
        return out
    for name, m in snap.items():
        if m.get("type") != "gauge":
            continue
        ts = m.get("ts")
        if isinstance(ts, (int, float)) and snap_ts - ts > after_s:
            out[name] = round(snap_ts - ts, 1)
    return out


def summary_from_state(state: dict) -> dict:
    kinds = state["kinds"]
    snapshot_event = state["snapshot"]
    snap = snapshot_event.get("metrics", {}) if snapshot_event else {}
    compile_stats = snapshot_event.get("compile", {}) if snapshot_event \
        else {}
    wall = (round(state["ts_max"] - state["ts_min"], 3)
            if state["ts_min"] is not None
            and state["ts_max"] is not None else 0.0)

    bp_shots = _metric(snap, "bp.shots")
    bp_conv = _metric(snap, "bp.converged")
    iters = snap.get("bp.iterations", {})
    osd_host_shots = _metric(snap, "osd.shots")
    osd_dev_shots = _metric(snap, "osd.device_shots")
    lat = snap.get("serve.latency_s", {})
    occ = snap.get("serve.batch_occupancy", {})
    serve_requests = _metric(snap, "serve.requests")
    serve = {
        "requests": serve_requests,
        "shots": _metric(snap, "serve.shots"),
        "batches": _metric(snap, "serve.batches"),
        "padded_shots": _metric(snap, "serve.padded_shots"),
        "errors": _metric(snap, "serve.errors"),
        "queue_depth_max": _metric(snap, "serve.queue_depth", "max"),
        "sessions": _metric(snap, "serve.sessions"),
        "session_compiles": _metric(snap, "serve.session.compiles"),
        "session_evictions": _metric(snap, "serve.session.evictions"),
        "occupancy_mean": (round(occ["mean"], 4)
                           if occ.get("mean") is not None else None),
        "latency_p50_s": _hist_quantile(lat, 0.50),
        "latency_p99_s": _hist_quantile(lat, 0.99),
        # wire accounting (ISSUE 15): framed bytes both ways + the last
        # negotiated codec, and the cross-session fused-dispatch counters
        "bytes_rx": _metric(snap, "serve.bytes_rx"),
        "bytes_tx": _metric(snap, "serve.bytes_tx"),
        "wire_codec_version": _metric(snap, "wire.codec_version") or None,
        "fused_dispatches": _metric(snap, "serve.fused.dispatches"),
        "fused_fallbacks": _metric(snap, "serve.fused.fallbacks"),
        "tenants": {
            name[len("serve.tenant."):-len(".requests")]: m.get("value", 0)
            for name, m in snap.items()
            if name.startswith("serve.tenant.")
            and name.endswith(".requests")
        },
    }
    # streaming decode counters (ISSUE 16): rendered, not silently dropped
    stream = {
        "opens": _metric(snap, "stream.opens"),
        "commits": _metric(snap, "stream.commits"),
        "cycles": _metric(snap, "stream.cycles"),
        "replays": _metric(snap, "stream.replays"),
        "shed": _metric(snap, "stream.shed"),
        "protocol_errors": _metric(snap, "stream.protocol_errors"),
        "open_streams": _metric(snap, "stream.open_streams"),
    }
    spans = {
        name[len("span."):-len(".seconds")]: m
        for name, m in snap.items()
        if name.startswith("span.") and m.get("type") == "histogram"
    }
    return {
        "events": kinds,
        "wall_s": wall,
        "shots": _metric(snap, "sim.shots"),
        "failures": _metric(snap, "sim.failures"),
        "runs": _metric(snap, "sim.runs"),
        "sweep_cells": _metric(snap, "sweep.cells"),
        "dispatches": _metric(snap, "driver.dispatches"),
        "batches": _metric(snap, "driver.batches"),
        "early_stops": _metric(snap, "driver.early_stops"),
        "drain_depth_max": _metric(snap, "driver.drain_depth", "max"),
        "bp": {
            "shots": bp_shots,
            "converged": bp_conv,
            "converged_fraction": (round(bp_conv / bp_shots, 6)
                                   if bp_shots else None),
            "iterations_mean": iters.get("mean"),
            "iterations_buckets": iters.get("buckets"),
            "iterations_counts": iters.get("counts"),
        },
        "osd": {
            "invocations": _metric(snap, "osd.invocations"),
            "host_shots": osd_host_shots,
            "device_shots": osd_dev_shots,
            "shots": osd_host_shots + osd_dev_shots,
            "host_round_trips": _metric(snap, "osd.host_round_trips"),
        },
        "serve": serve,
        "stream": stream,
        "jax": {
            "retraces": compile_stats.get(
                "jax.retraces", _metric(snap, "jax.retraces")),
            "lowerings": compile_stats.get(
                "jax.lowerings", _metric(snap, "jax.lowerings")),
            "backend_compiles": compile_stats.get(
                "jax.backend_compiles", _metric(snap, "jax.backend_compiles")),
            "backend_compile_s": round(
                _metric(snap, "jax.backend_compiles.seconds"), 3),
            "retrace_source": compile_stats.get("source"),
        },
        "stale_gauges": stale_gauges(
            snap, snapshot_event.get("ts") if snapshot_event else None),
        "spans": {
            name: {"count": m["count"], "total_s": round(m["sum"], 4),
                   "mean_s": (round(m["sum"] / m["count"], 5)
                              if m["count"] else None),
                   "p50_s": (round(_hist_quantile(m, 0.50), 5)
                             if _hist_quantile(m, 0.50) is not None
                             else None),
                   "p95_s": (round(_hist_quantile(m, 0.95), 5)
                             if _hist_quantile(m, 0.95) is not None
                             else None)}
            for name, m in sorted(spans.items())
        },
        "snapshot": snap,
    }


def render_trace_tree(spans: list[dict]) -> str:
    """One trace's spans as an indented tree (the --trace view): name,
    duration, amortization factor and error per span."""
    from qldpc_fault_tolerance_tpu.utils import tracing

    tree = tracing.trace_tree(spans)

    def _line(node, depth):
        s = node["span"]
        parts = [f"{'  ' * depth}{s.get('name')}",
                 f"{1e3 * float(s.get('dur_s', 0.0)):.3f} ms"]
        if s.get("amortized_over", 1) not in (None, 1):
            parts.append(f"(amortized /{s['amortized_over']})")
        if s.get("ok") is False or s.get("error"):
            parts.append(f"ERROR: {s.get('error', '?')}")
        rows.append("  ".join(parts))
        for child in sorted(node["children"],
                            key=lambda n: n["span"].get("ts") or 0.0):
            _line(child, depth + 1)

    rows: list[str] = []
    for root in tree["roots"]:
        _line(root, 0)
    return "\n".join(rows) if rows else "(no spans)"


def render_traces(events: list[dict], *, limit: int = 50,
                  slow_s=None, errored_only: bool = False) -> str:
    """Recent traces, newest-first (the --traces view)."""
    from qldpc_fault_tolerance_tpu.utils import tracing

    rows = tracing.trace_summaries(events, limit=limit, slow_s=slow_s,
                                   errored_only=errored_only)
    if not rows:
        return "(no trace events)"
    L = [f"{'trace_id':<34}{'spans':>6}{'max_ms':>10}{'total_ms':>10}"
         f"  names"]
    for r in rows:
        L.append(f"{r['trace_id']:<34}{r['spans']:>6}"
                 f"{1e3 * r['max_dur_s']:>10.3f}"
                 f"{1e3 * r['total_dur_s']:>10.3f}"
                 f"  {','.join(r['names'])}"
                 + ("  [ERRORED]" if r["errored"] else ""))
    return "\n".join(L)


def _bar(n: int, peak: int, width: int = 30) -> str:
    return "#" * max(1 if n else 0, round(width * n / peak)) if peak else ""


def render(summary: dict, title: str = "") -> str:
    """The human table."""
    s = summary
    L = [f"== qldpc telemetry report{': ' + title if title else ''} =="]
    ev = ", ".join(f"{v} {k}" for k, v in sorted(s["events"].items()))
    L.append(f"events: {ev}   (span {s['wall_s']}s wall)")
    L.append("")
    L.append("-- runs --")
    rows = [
        ("shots", s["shots"]), ("failures", s["failures"]),
        ("wer runs", s["runs"]), ("sweep cells", s["sweep_cells"]),
        ("dispatches", s["dispatches"]), ("batches", s["batches"]),
        ("early stops", s["early_stops"]),
        ("drain depth (max)", s["drain_depth_max"]),
    ]
    if s["shots"]:
        rows.insert(2, ("failure fraction",
                        round(s["failures"] / s["shots"], 6)))
    for k, v in rows:
        L.append(f"  {k:<22}{v}")
    bp = s["bp"]
    L.append("-- bp decoder --")
    L.append(f"  {'decoder shots':<22}{bp['shots']}")
    if bp["shots"]:
        L.append(f"  {'converged':<22}{bp['converged']}"
                 f"  ({100 * bp['converged_fraction']:.2f}%)")
        if bp["iterations_mean"] is not None:
            L.append(f"  iterations to convergence "
                     f"(mean {bp['iterations_mean']:.2f}):")
            buckets = bp["iterations_buckets"] or []
            counts = bp["iterations_counts"] or []
            peak = max(counts) if counts else 0
            labels = [f"<={b}" for b in buckets] + [f">{buckets[-1]}"
                                                    if buckets else ">"]
            for lab, n in zip(labels, counts):
                if n:
                    L.append(f"    {lab:>6} {n:>10}  {_bar(n, peak)}")
    srv = s.get("serve") or {}
    if srv.get("requests"):
        L.append("-- serve (decode service) --")
        L.append(f"  {'requests':<22}{srv['requests']}"
                 f"  ({srv['errors']} failed)")
        L.append(f"  {'shots':<22}{srv['shots']}"
                 f"  (+{srv['padded_shots']} pad)")
        L.append(f"  {'batches':<22}{srv['batches']}"
                 + (f"  (occupancy {srv['occupancy_mean']:.2f})"
                    if srv.get("occupancy_mean") is not None else ""))
        p50, p99 = srv.get("latency_p50_s"), srv.get("latency_p99_s")
        if p50 is not None:
            L.append(f"  {'latency p50/p99':<22}"
                     f"{1e3 * p50:.1f} / {1e3 * p99:.1f} ms")
        q_stale = s.get("stale_gauges", {}).get("serve.queue_depth")
        L.append(f"  {'queue depth (max)':<22}{srv['queue_depth_max']}"
                 + (f"  [STALE {q_stale}s]" if q_stale is not None else ""))
        if srv.get("bytes_rx") or srv.get("bytes_tx"):
            codec = srv.get("wire_codec_version")
            L.append(f"  {'wire bytes rx/tx':<22}"
                     f"{srv['bytes_rx']} / {srv['bytes_tx']}"
                     + (f"  (codec v{codec})" if codec else ""))
        if srv.get("fused_dispatches") or srv.get("fused_fallbacks"):
            L.append(f"  {'fused dispatches':<22}{srv['fused_dispatches']}"
                     f"  ({srv['fused_fallbacks']} fallbacks)")
        L.append(f"  {'sessions':<22}{srv['sessions']}"
                 f"  ({srv['session_compiles']} compiles, "
                 f"{srv['session_evictions']} evictions)")
        for tenant, n in sorted(srv.get("tenants", {}).items()):
            L.append(f"  {'tenant ' + tenant:<22}{n}")
    stm = s.get("stream") or {}
    if stm.get("opens") or stm.get("commits"):
        L.append("-- stream (overlap-commit decode) --")
        L.append(f"  {'streams opened':<22}{stm['opens']}"
                 f"  ({stm['open_streams']} still open)")
        L.append(f"  {'windows committed':<22}{stm['commits']}"
                 f"  ({stm['cycles']} cycles)")
        if stm.get("replays"):
            L.append(f"  {'replayed seqs':<22}{stm['replays']}")
        if stm.get("shed") or stm.get("protocol_errors"):
            L.append(f"  {'shed / proto errors':<22}{stm['shed']}"
                     f" / {stm['protocol_errors']}")
    osd = s["osd"]
    L.append("-- osd --")
    L.append(f"  {'invocations':<22}{osd['invocations']}")
    L.append(f"  {'shots':<22}{osd['shots']}"
             f"  (host {osd['host_shots']}, device {osd['device_shots']})")
    L.append(f"  {'host round-trips':<22}{osd['host_round_trips']}")
    j = s["jax"]
    L.append("-- jax compile --")
    L.append(f"  retraces {j['retraces']}   lowerings {j['lowerings']}   "
             f"backend compiles {j['backend_compiles']} "
             f"({j['backend_compile_s']}s)"
             + (f"   [source: {j['retrace_source']}]"
                if j.get("retrace_source") else ""))
    if s["spans"]:
        L.append("-- spans --")
        w = max(len(n) for n in s["spans"]) + 2
        L.append(f"  {'name':<{w}}{'count':>7}{'total_s':>12}{'mean_s':>12}"
                 f"{'p50_s':>12}{'p95_s':>12}")
        for name, m in s["spans"].items():
            L.append(f"  {name:<{w}}{m['count']:>7}{m['total_s']:>12}"
                     f"{m['mean_s']:>12}"
                     f"{m.get('p50_s') if m.get('p50_s') is not None else '-':>12}"
                     f"{m.get('p95_s') if m.get('p95_s') is not None else '-':>12}")
    if s.get("stale_gauges"):
        L.append("-- stale gauges (frozen values, not current state) --")
        for name, age in sorted(s["stale_gauges"].items()):
            L.append(f"  {name:<30}last set {age}s before snapshot")
    return "\n".join(L)


def build_series_store(events: list[dict]):
    """Rebuild a utils.timeseries.SeriesStore from the stream's
    ``snapshot`` events; returns (store, n_snapshots, last_ts).  The same
    ingest path the live scraper uses, so rate/quantile derivations are
    identical on- and off-line."""
    from qldpc_fault_tolerance_tpu.utils import timeseries

    store = timeseries.SeriesStore()
    n, last_ts = 0, None
    for e in events:
        if e.get("kind") != "snapshot":
            continue
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        store.ingest(ts, e.get("metrics", {}))
        n += 1
        last_ts = ts
    return store, n, last_ts


def render_rates(events: list[dict], window_s: float) -> str:
    """The --rates view: counter rates and windowed histogram p50/p99 over
    the trailing window, derived from the rebuilt time-series store."""
    store, n, last_ts = build_series_store(events)
    if n == 0:
        return "(no snapshot events — enable the scraper's " \
               "emit_snapshot_events or telemetry.session())"
    L = [f"== windowed rates (window {window_s:g}s, {n} snapshots) =="]
    if n == 1:
        # nothing to difference: lifetime averages over the event span
        state = fold_events(new_fold_state(), events)
        wall = ((state["ts_max"] - state["ts_min"])
                if state["ts_min"] is not None else 0.0)
        L[0] += "  [single snapshot: lifetime averages over "\
            f"{round(wall, 1)}s]"
        snap = state["snapshot"].get("metrics", {})
        for name, m in sorted(snap.items()):
            if m.get("type") == "counter" and m["value"] and wall > 0:
                L.append(f"  {name:<34}{m['value'] / wall:>12.2f}/s")
        return "\n".join(L)
    rates = []
    hists = []
    gauges = []
    for name in store.names():
        kind = store.kind(name)
        if kind == "counter":
            r = store.rate(name, window_s, now=last_ts)
            if r:
                rates.append((name, r))
        elif kind == "histogram":
            got = store.window_hist(name, window_s, now=last_ts)
            if got is None or not got[3]:
                continue
            buckets, counts, dsum, dcount = got
            p50 = store.quantile(name, 0.50, window_s, now=last_ts)
            p99 = store.quantile(name, 0.99, window_s, now=last_ts)
            hists.append((name, dcount, dsum, p50, p99))
        elif kind == "gauge":
            v = store.last_value(name)
            set_ts = store.gauge_set_ts(name)
            age = (last_ts - set_ts
                   if isinstance(set_ts, (int, float)) else None)
            gauges.append((name, v, age))
    if rates:
        L.append("-- counter rates --")
        for name, r in sorted(rates, key=lambda kv: -kv[1]):
            L.append(f"  {name:<34}{r:>12.2f}/s")
    if hists:
        L.append("-- windowed histograms --")
        L.append(f"  {'name':<34}{'count':>9}{'mean':>11}{'p50':>11}"
                 f"{'p99':>11}")
        for name, dcount, dsum, p50, p99 in sorted(hists):
            mean = dsum / dcount if dcount else None
            fmt = lambda v: f"{v:.4g}" if v is not None else "-"
            L.append(f"  {name:<34}{dcount:>9}{fmt(mean):>11}"
                     f"{fmt(p50):>11}{fmt(p99):>11}")
    if gauges:
        L.append("-- gauges (last value) --")
        for name, v, age in sorted(gauges):
            mark = (f"  [STALE {age:.1f}s]"
                    if age is not None and age > window_s else "")
            L.append(f"  {name:<34}{v!s:>12}{mark}")
    return "\n".join(L)


def load_fleet(source: str) -> dict:
    """Fetch the fleet view from a gateway base URL (GET /varz, /healthz,
    /alertz) or load a file holding its /varz JSON."""
    if source.startswith(("http://", "https://")):
        import urllib.error
        import urllib.request

        def get(path):
            try:
                with urllib.request.urlopen(source.rstrip("/") + path,
                                            timeout=10.0) as resp:
                    body = resp.read()
            except urllib.error.HTTPError as err:
                # the gateway's /healthz deliberately answers 503 while
                # hosts are down — that degraded body is exactly the view
                # the report exists to show
                body = err.read()
            return json.loads(body.decode("utf-8"))

        out = {"varz": get("/varz")}
        for key, path in (("healthz", "/healthz"), ("alertz", "/alertz")):
            try:
                out[key] = get(path)
            except Exception:  # a /varz-only source still renders
                out[key] = None
        return out
    with open(source, encoding="utf-8") as fh:
        return {"varz": json.load(fh), "healthz": None, "alertz": None}


def render_fleet(fleet: dict) -> str:
    """The --fleet block: per-host up/down, merged counter totals, active
    alerts (from a serve.fleet gateway's endpoints)."""
    varz = fleet.get("varz") or {}
    healthz = fleet.get("healthz")
    alertz = fleet.get("alertz")
    L = ["== fleet (federation gateway) =="]
    targets = varz.get("targets", {})
    L.append(f"  hosts: {len(targets)}   scrapes: {varz.get('scrapes', 0)}")
    if healthz:
        for label, h in sorted(healthz.get("hosts", {}).items()):
            mark = "up" if h.get("up") else "DOWN"
            ok = "" if h.get("ok") or not h.get("up") else "  [not ok]"
            age = h.get("last_ok_age_s")
            L.append(f"  {label:<20}{mark:<6}"
                     + (f"last ok {age}s ago" if age is not None
                        else "never scraped") + ok)
        if healthz.get("down"):
            L.append(f"  DOWN: {', '.join(healthz['down'])}")
    merged = varz.get("merged", {})
    counters = {k: v for k, v in merged.items()
                if v.get("type") == "counter" and v.get("value")}
    if counters:
        L.append("  -- merged counter totals (bit-exact sums) --")
        for name, m in sorted(counters.items()):
            L.append(f"    {name:<32}{m['value']}")
    if varz.get("merge_skipped"):
        L.append(f"  merge skipped (boundary mismatch): "
                 f"{', '.join(varz['merge_skipped'])}")
    placement = varz.get("placement")
    if placement:
        L.append("  -- family placement (router) --")
        L.append(f"    {'family':<16}{'owner':<12}{'successor':<12}epoch")
        for fam, p in sorted(placement.items()):
            L.append(f"    {fam:<16}{p.get('owner', '?'):<12}"
                     f"{p.get('successor') or '-':<12}{p.get('epoch', '?')}")
        if varz.get("down_hosts"):
            L.append(f"    DOWN hosts: {', '.join(varz['down_hosts'])}")
    handoffs = varz.get("handoffs")
    if handoffs:
        L.append("  -- last handoffs --")
        for fam, h in sorted(handoffs.items()):
            age = h.get("age_s")
            age_s = f"{age:.1f}s ago" if isinstance(age, (int, float)) \
                else "?"
            L.append(f"    {fam:<16}{h.get('from', '?')} -> "
                     f"{h.get('to', '?')}  epoch {h.get('epoch', '?')}  "
                     f"{age_s}  ({h.get('reason', '?')})")
    if alertz and alertz.get("active"):
        L.append("  -- active alerts --")
        for a in alertz["active"]:
            L.append(f"    [{a.get('severity', '?'):<8}] "
                     f"{a.get('host', '?')}/{a.get('alert', '?')} "
                     f"({a.get('state', 'firing')})")
    return "\n".join(L)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", nargs="?", default=None,
                    help="telemetry JSONL stream to render (optional when "
                         "only --fleet is asked for)")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as json instead of the table")
    ap.add_argument("--prometheus", action="store_true",
                    help="emit the final snapshot in Prometheus text format")
    ap.add_argument("--follow", action="store_true",
                    help="tail an active sink and re-render incrementally "
                         "(Ctrl-C to stop)")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="--follow poll interval in seconds (default 1)")
    ap.add_argument("--traces", action="store_true",
                    help="list recent traces (newest-first) from the "
                         "stream's trace events")
    ap.add_argument("--trace", metavar="ID",
                    help="render one trace id's span tree")
    ap.add_argument("--slow-ms", type=float, default=None,
                    help="--traces: only traces with a span at least this "
                         "slow")
    ap.add_argument("--errored", action="store_true",
                    help="--traces: only traces with an errored span")
    ap.add_argument("--rates", type=float, metavar="WINDOW_S", default=None,
                    help="render counter rates + windowed histogram "
                         "quantiles over this trailing window (needs the "
                         "stream's periodic snapshot events)")
    ap.add_argument("--fleet", metavar="URL_OR_JSON", default=None,
                    help="append a fleet block from a federation gateway "
                         "(base URL, or a file with its /varz JSON)")
    args = ap.parse_args(argv)

    if args.jsonl is None:
        # fleet-only mode: an operator on a gateway box has no JSONL
        if not args.fleet or args.follow or args.traces or args.trace \
                or args.rates is not None or args.prometheus or args.json:
            ap.error("a telemetry JSONL stream is required "
                     "(only a bare --fleet URL works without one)")
        print(render_fleet(load_fleet(args.fleet)))
        return 0

    if args.follow:
        if args.traces or args.trace:
            # silently rendering the summary instead of the asked-for
            # trace view would be the wrong output with no explanation
            ap.error("--traces/--trace are not supported with --follow; "
                     "run them against the stream without --follow")
        return follow(args.jsonl, args.interval)

    events = load_events(args.jsonl)
    if not events:
        print(f"no events in {args.jsonl}", file=sys.stderr)
        return 1
    if args.trace:
        from qldpc_fault_tolerance_tpu.utils import tracing

        spans = tracing.traces_from_records(events).get(args.trace, [])
        if not spans:
            print(f"no spans for trace {args.trace!r}", file=sys.stderr)
            return 1
        print(render_trace_tree(spans))
        return 0
    if args.traces:
        print(render_traces(
            events, slow_s=(None if args.slow_ms is None
                            else args.slow_ms / 1e3),
            errored_only=args.errored))
        return 0
    if args.rates is not None:
        print(render_rates(events, args.rates))
        if args.fleet:
            print(render_fleet(load_fleet(args.fleet)))
        return 0
    summary = summarize(events)
    if args.prometheus:
        from qldpc_fault_tolerance_tpu.utils import telemetry

        sys.stdout.write(telemetry.prometheus_text(summary["snapshot"]))
        return 0
    if args.json:
        out = dict(summary)
        out.pop("snapshot")  # the raw registry dump is --prometheus/json-able
        print(json.dumps(out, indent=1, default=str))
        return 0
    print(render(summary, title=os.path.basename(args.jsonl)))
    if args.fleet:
        print(render_fleet(load_fleet(args.fleet)))
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:  # e.g. `... | head` — not an error
        raise SystemExit(0)
