"""Per-code decoder-variant sensitivity for the circuit-level p_c offset.

The fit-sensitivity analysis (PARITY_r4.md) shows the notebook ThresholdEst
is invariant to uniform WER scaling and to per-code log-log tilts — fitted
p_c responds ONLY to the relative suppression between family members.  So
any decoder-implementation difference vs the reference's `ldpc` binaries can
move p_c only through its CODE-SIZE-DEPENDENT effect (dec1 max_iter =
int(N/30) = 1/5/11 for toric d5/d9/d13).  This experiment measures, per
code, how much plausible ldpc-variant hypotheses move the circuit-level WER
on one fixed detector sample set:

  arm mi-1 / mi+1 : one fewer/more dec1 BP iteration (iteration-count
                    off-by-one semantics)
  arm mi2-       : final BPOSD BP stage one fewer iteration

Ratios WER(arm)/WER(base) feed back into the recorded round-3 grids
(PARITY_results.jsonl) to see whether any hypothesis reproduces the
published p_c (scripts/ab_fit_propagation.py).

Usage:
  JAX_PLATFORMS=cpu python scripts/ab_iteration.py --cycles 20 --p 2e-3
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))


def run_code(d: int, cycles: int, p: float, shots: int, arms):
    import jax
    import jax.numpy as jnp

    from parity import make_circuit_decoders
    from qldpc_fault_tolerance_tpu.codes import hgp, ring_code
    from qldpc_fault_tolerance_tpu.sim import CodeSimulator_Circuit
    from qldpc_fault_tolerance_tpu.sim.circuit import _decode_rounds_given

    code = hgp(ring_code(d), ring_code(d), name=f"toric_d{d}")
    N = code.hx.shape[1]
    error_params = {"p_i": 0, "p_state_p": 0, "p_m": 0, "p_CX": p,
                    "p_idling_gate": 0}
    mi1 = int(N / 30)
    mi2 = int(N / 10)

    def make_sim(mi1_, mi2_, method1="minimum_sum", method2="minimum_sum",
                 msf1=0.625, msf2=0.625):
        dec1, dec2 = make_circuit_decoders(
            code, p, msf1=msf1, msf2=msf2, mi1=mi1_, mi2=mi2_,
            method1=method1, method2=method2)
        sim = CodeSimulator_Circuit(
            code=code, decoder1_z=dec1, decoder2_z=dec2, p=p,
            num_cycles=cycles, error_params=error_params, seed=0)
        sim._generate_circuit()
        return sim

    # one fixed detector sample set per code
    base = make_sim(mi1, mi2)
    chunk = 5000
    dets_all, obs_all = [], []
    for i in range(0, shots, chunk):
        b = min(chunk, shots - i)
        dd, oo = base._sampler.sample(jax.random.PRNGKey(900 + i), b)
        dets_all.append(np.asarray(dd))
        obs_all.append(np.asarray(oo))
    dets = np.concatenate(dets_all)
    obs = np.concatenate(obs_all)

    out = {}
    for name, (d1_, d2_, *rest) in arms.items():
        sim = make_sim(mi1 + d1_, mi2 + d2_, *rest)
        f = 0
        for i in range(0, shots, chunk):
            b = min(chunk, shots - i)
            pending = _decode_rounds_given(
                sim._cfg(b), sim._dev_state,
                jnp.asarray(dets[i:i + b]), jnp.asarray(obs[i:i + b]))
            f += int(np.asarray(sim._finish_batch(pending)).sum())
        out[name] = f
        print(f"  d{d:<2d} mi1={max(mi1 + d1_, 1):<2d} mi2={mi2 + d2_:<3d} "
              f"arm {name:6s}: {f:6d}/{shots} = {f / shots:.5f}", flush=True)
    return {"d": d, "mi1": mi1, "mi2": mi2, "shots": shots,
            "failures": out}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cycles", type=int, default=20)
    ap.add_argument("--p", type=float, default=2e-3)
    ap.add_argument("--out", default=os.path.join(REPO, "AB_ITERATION.json"))
    args = ap.parse_args()
    arms = json.loads(os.environ.get("AB_ARMS", "null")) or {
        "base": (0, 0), "mi-1": (-1, 0), "mi+1": (1, 0), "mi2-1": (0, -1)}
    results = []
    for d, shots in ((5, 60000), (9, 30000), (13, 15000)):
        print(f"toric d{d}, cycles={args.cycles}, p={args.p}:", flush=True)
        results.append(run_code(d, args.cycles, args.p, shots, arms))
    with open(args.out, "w") as f:
        json.dump({"cycles": args.cycles, "p": args.p,
                   "results": results}, f, indent=1)
    print(f"written to {args.out}")


if __name__ == "__main__":
    main()
